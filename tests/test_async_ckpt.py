"""Async checkpointing (doc/performance.md "Zero-stall host"): the
AsyncCheckpointer's drain ordering / drop-oldest / error-propagation
contracts (gated fakes, no wall-clock races), the event-ordering
regression proving the step loop never blocks on serialize/fsync, the
metrics assertion that ``ckpt.blocked_s`` is snapshot-only, and the
chaos drills — hard-kill and SIGTERM between the async snapshot and the
rename must leave a verifiable, auto-resumable checkpoint."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import CheckpointError, faultinject
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer.async_ckpt import AsyncCheckpointer, snapshot_to_host

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")
    faultinject.configure("")


def _params(offset=0.0):
    return {"w": jnp.arange(12.0).reshape(3, 4) + offset, "b": jnp.ones((4,)) + offset}


class _GatedWriter:
    """A write_fn whose writes block until released, recording an event
    log — the ordering (not wall-clock) seam the unit tests drive."""

    def __init__(self, events=None, gate_timeout=20.0):
        self.events = events if events is not None else []
        self.gates = {}
        self.gate_timeout = gate_timeout
        self.written = []

    def gate(self, pass_id):
        self.gates[pass_id] = threading.Event()
        return self.gates[pass_id]

    def __call__(self, save_dir, pass_id, params, opt_state=None, **kw):
        self.events.append(("write_start", pass_id))
        g = self.gates.get(pass_id)
        if g is not None:
            # a timed-out gate means the expected interleaving never
            # happened; the write proceeds so nothing deadlocks and the
            # event log carries the proof of the wrong order
            g.wait(self.gate_timeout)
        self.written.append(pass_id)
        self.events.append(("write_done", pass_id))
        return os.path.join(save_dir, ckpt.PASS_FMT % pass_id)


# ------------------------------------------------- unit: ordering contracts


@pytest.mark.perf
def test_save_never_blocks_on_write():
    """Event-ordering regression: with async checkpointing on, save()
    must return BEFORE the background serialize/fsync even starts to
    finish — proven by a gate, not by timing."""
    w = _GatedWriter()
    gate = w.gate(0)
    ac = AsyncCheckpointer("/tmp/nowhere", write_fn=w)
    ac.save(0, _params())
    # the write is gated shut: save() returning at all proves the step
    # loop side never waited on it
    w.events.append(("save_returned", 0))
    # the claim race is real: with inflight_limit=1, a second save
    # landing before the writer CLAIMS pass 0 drops it (drop-oldest-
    # pending, per contract) — the `paddle race` async_ckpt spec
    # explores that schedule deliberately. This test pins the write
    # ordering, so wait out the claim instead of racing it.
    deadline = time.monotonic() + 5
    while ac._active is None and time.monotonic() < deadline:
        time.sleep(0.001)
    ac.save(1, _params(1.0))
    w.events.append(("save_returned", 1))
    gate.set()
    ac.drain()
    order = w.events
    assert order.index(("save_returned", 0)) < order.index(("write_done", 0)), order
    assert order.index(("save_returned", 1)) < order.index(("write_done", 0)), order
    # order-preserving: pass 0's write completes before pass 1's starts
    assert w.written == [0, 1], w.written


def test_drain_blocks_until_all_writes_durable():
    w = _GatedWriter()
    gate = w.gate(0)
    ac = AsyncCheckpointer("/tmp/nowhere", inflight_limit=2, write_fn=w)
    ac.save(0, _params())
    ac.save(1, _params(1.0))
    assert ac.inflight() >= 1
    released = threading.Timer(0.2, gate.set)
    released.start()
    ac.drain()
    # drain returned => every enqueued write ran to completion, in order
    assert w.written == [0, 1]
    assert ac.inflight() == 0


def test_drain_empty_is_immediate_and_timeout_raises():
    w = _GatedWriter()
    ac = AsyncCheckpointer("/tmp/nowhere", write_fn=w)
    t0 = time.monotonic()
    ac.drain()  # nothing pending: no writer thread needed, returns now
    assert time.monotonic() - t0 < 1.0
    gate = w.gate(5)
    ac.save(5, _params())
    with pytest.raises(CheckpointError, match="timed out"):
        ac.drain(timeout=0.3)
    gate.set()
    ac.drain()


def test_drop_oldest_pending_keeps_active_and_newest():
    w = _GatedWriter()
    gate = w.gate(0)
    ac = AsyncCheckpointer("/tmp/nowhere", inflight_limit=1, write_fn=w)
    ac.save(0, _params())          # becomes the active (gated) write
    deadline = time.monotonic() + 5
    while ("write_start", 0) not in w.events and time.monotonic() < deadline:
        time.sleep(0.01)           # writer thread must CLAIM it first
    ac.save(1, _params(1.0))       # queued
    ac.save(2, _params(2.0))       # queue over limit -> pass 1 dropped
    gate.set()
    ac.drain()
    assert w.written == [0, 2], w.written
    assert ac.dropped == 1
    assert obs.registry().counter("ckpt.async_dropped").value == 1


def test_writer_error_surfaces_on_next_save_and_drain():
    calls = []

    def bad_write(save_dir, pass_id, params, opt_state=None, **kw):
        calls.append(pass_id)
        if pass_id == 0:
            raise OSError("disk on fire")
        return "ok"

    ac = AsyncCheckpointer("/tmp/nowhere", write_fn=bad_write)
    ac.save(0, _params())
    # the failure lands on the NEXT interaction, never silently
    with pytest.raises(CheckpointError, match="disk on fire"):
        ac.drain()
    # the error was consumed: the pipeline keeps working afterwards
    ac.save(1, _params(1.0))
    ac.drain()
    assert calls == [0, 1]

    ac2 = AsyncCheckpointer("/tmp/nowhere", write_fn=bad_write)
    calls.clear()

    def bad0(save_dir, pass_id, params, opt_state=None, **kw):
        raise OSError("still on fire")

    ac2._write_fn = bad0
    ac2.save(0, _params())
    ac2_deadline = time.monotonic() + 5
    while ac2.inflight() and time.monotonic() < ac2_deadline:
        time.sleep(0.01)
    with pytest.raises(CheckpointError, match="still on fire"):
        ac2.save(1, _params())


def test_hangwatch_pinged_from_writer_thread():
    pings = []

    class FakeWatch:
        def ping(self, pass_id=None, step=None):
            pings.append((threading.current_thread().name, pass_id))

    ac = AsyncCheckpointer("/tmp/nowhere", hangwatch=FakeWatch(),
                           write_fn=_GatedWriter())
    ac.save(3, _params())
    ac.drain()
    writer_pings = [p for p in pings if p[0] == "pt-ckpt-writer"]
    assert len(writer_pings) >= 2 and writer_pings[0][1] == 3, pings


def test_snapshot_to_host_returns_numpy_trees():
    host = snapshot_to_host({"a": jnp.ones((2, 3)), "nested": {"b": jnp.zeros(4)}})
    assert isinstance(host["a"], np.ndarray)
    assert isinstance(host["nested"]["b"], np.ndarray)
    np.testing.assert_array_equal(host["a"], np.ones((2, 3)))


def test_real_write_fn_produces_verifiable_checkpoint(tmp_path):
    """The background writer runs the UNCHANGED durable protocol: the
    landed directory must verify against its manifest like a sync save."""
    ac = AsyncCheckpointer(str(tmp_path))
    ac.save(0, _params(), extra_meta={"batch_id": 7})
    ac.drain()
    path = os.path.join(str(tmp_path), ckpt.PASS_FMT % 0)
    assert ckpt.verify_checkpoint(path) == []
    params, _, meta = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(np.asarray(params["w"]), np.asarray(_params()["w"]))
    assert meta["batch_id"] == 7
    # step-loop accounting exists and is tiny next to the real write
    assert obs.registry().counter("ckpt.blocked_s").value > 0.0
    assert obs.registry().counter("ckpt.write_s").value > 0.0


# ------------------------------------------------ trainer-level integration

_CFG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list={train_list!r}, test_list=None,
                        module="synthetic_bow", obj="process")
settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
data = data_layer(name="word", size=100)
output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=output, label=label))
"""


def _mk_trainer(tmp_path, **flag_kw):
    sys.path.insert(0, PROVIDER_DIR)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    cfg_path = tmp_path / "cfg.py"
    cfg_path.write_text(_CFG.format(train_list=str(train_list)))
    flags = _Flags(config=str(cfg_path), num_passes=2, log_period=0,
                   save_dir=str(tmp_path / "out"), async_checkpoint=True,
                   **flag_kw)
    return Trainer(parse_config(str(cfg_path)), flags), flags


@pytest.fixture(autouse=True)
def _provider_path():
    sys.path.insert(0, PROVIDER_DIR)
    yield
    while PROVIDER_DIR in sys.path:
        sys.path.remove(PROVIDER_DIR)


def test_async_trainer_blocked_is_snapshot_only(tmp_path, monkeypatch):
    """Acceptance: with --async_checkpoint on, ckpt.blocked_s per save
    is only the device→host snapshot — asserted via the metrics stream
    against writes slowed by an injected per-file delay."""
    real_write = ckpt._write_file

    def slow_write(path, writer, mode="wb"):
        time.sleep(0.15)
        return real_write(path, writer, mode)

    monkeypatch.setattr(ckpt, "_write_file", slow_write)
    trainer, flags = _mk_trainer(tmp_path)
    trainer.train()
    out = str(tmp_path / "out")
    # every save landed durable despite the background path
    assert ckpt.find_restorable_checkpoint(out) is not None
    recs = list(obs.read_records(os.path.join(out, "metrics.jsonl")))
    snaps = [r for r in recs if r.get("kind") == "checkpoint"
             and r.get("op") == "snapshot"]
    saves = [r for r in recs if r.get("kind") == "checkpoint"
             and r.get("op") == "save"]
    assert snaps and saves
    # each slowed save writes >= 3 files (params, slots, meta, manifest)
    # so >= 0.45s background; the step loop paid only the snapshot
    assert all(s["duration_s"] < 0.1 for s in snaps), snaps
    assert all(s["duration_s"] > 0.4 for s in saves), saves
    # registry after train(): the final drain has completed, so both
    # sides of the split are fully accounted (a pass_end snapshot can
    # legitimately precede an in-flight write's completion)
    assert obs.registry().counter("ckpt.blocked_s").value < 0.2
    assert obs.registry().counter("ckpt.write_s").value > 0.4


@pytest.mark.perf
def test_step_loop_overlaps_background_write(tmp_path):
    """Event-ordering (not wall-clock): pass 1's training starts while
    pass 0's checkpoint write is still gated shut — if save() blocked on
    serialize/fsync, the gate would only open via its failure timeout
    and the event order would betray it."""
    from paddle_tpu.trainer import trainer as trainer_mod

    trainer, flags = _mk_trainer(tmp_path)
    events = []
    w = _GatedWriter(events=events)
    gate = w.gate(0)
    trainer._async_ckpt._write_fn = w

    orig = trainer_mod.Trainer.train_one_pass

    def wrapped(self, pass_id, provider, rng):
        events.append(("pass_start", pass_id))
        if pass_id == 1:
            gate.set()  # pass 1 is running: NOW the write may finish
        return orig(self, pass_id, provider, rng)

    trainer_mod.Trainer.train_one_pass = wrapped
    try:
        trainer.train()
    finally:
        trainer_mod.Trainer.train_one_pass = orig
    assert events.index(("pass_start", 1)) < events.index(("write_done", 0)), events
    assert w.written == [0, 1], w.written


def test_async_failed_write_aborts_run_loudly(tmp_path):
    trainer, flags = _mk_trainer(tmp_path)

    def doomed(save_dir, pass_id, params, opt_state=None, **kw):
        raise OSError("shared fs went away")

    trainer._async_ckpt._write_fn = doomed
    with pytest.raises(CheckpointError, match="shared fs went away"):
        trainer.train()


# --------------------------------------------------------- chaos drills

_CHILD = """
import sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {providers!r})
import os
os.chdir({ws!r})
from paddle_tpu.utils.backend_guard import ensure_cpu_mesh
ensure_cpu_mesh(1)
from paddle_tpu.resilience import faultinject
faultinject.configure({fault_spec!r})
from paddle_tpu.config import parse_config
from paddle_tpu.trainer import Trainer
from paddle_tpu.utils.flags import _Flags

open("train.list", "w").write("1\\n2\\n")
open("cfg.py", "w").write('''{cfg}''')
cfg = parse_config("cfg.py")
flags = _Flags(config="cfg.py", num_passes=3, log_period=0, save_dir="out",
               async_checkpoint=True, init_model_path={init!r})
t = Trainer(cfg, flags)
t.train()
print("TRAIN_DONE start_pass=%d preempted=%s" % (t.start_pass, t.preempted),
      flush=True)
"""

_CHILD_CFG = """
from paddle_tpu.trainer_config_helpers import *
define_py_data_sources2(train_list="train.list", test_list=None,
                        module="synthetic_bow", obj="process")
settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
data = data_layer(name="word", size=100)
output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
label = data_layer(name="label", size=2)
outputs(classification_cost(input=output, label=label))
"""


def _run_child(ws, fault_spec="", init="", timeout=240):
    code = _CHILD.format(repo=REPO, providers=PROVIDER_DIR, ws=str(ws),
                         fault_spec=fault_spec, cfg=_CHILD_CFG, init=init)
    return subprocess.run(
        [sys.executable, "-c", code], cwd=ws, timeout=timeout,
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


@pytest.mark.chaos
def test_hard_kill_mid_async_write_leaves_restorable_checkpoint(tmp_path):
    """Die (os._exit, no cleanup) inside the SECOND background write,
    between its snapshot and its rename: the pass-0 checkpoint must
    still verify via `paddle check-checkpoint`, and --init_model_path=
    auto must restore it and finish the run."""
    r = _run_child(tmp_path, fault_spec="checkpoint.rename=exit@2")
    assert "TRAIN_DONE" not in (r.stdout or ""), r.stdout  # it really died
    out = str(tmp_path / "out")
    assert ckpt.verify_checkpoint(os.path.join(out, "pass-00000")) == []
    from paddle_tpu import cli

    assert cli.main(["check-checkpoint", os.path.join(out, "pass-00000")]) == 0
    # auto-resume: restores the durable checkpoint and completes
    r2 = _run_child(tmp_path, init="auto")
    assert "TRAIN_DONE" in r2.stdout, r2.stdout + r2.stderr
    assert "start_pass=1" in r2.stdout, r2.stdout
    assert ckpt.find_restorable_checkpoint(out).endswith("pass-00002")


@pytest.mark.chaos
def test_sigterm_drains_async_save_before_clean_exit(tmp_path):
    """SIGTERM between the async snapshot and the rename: the
    preemption path must DRAIN the writer — the checkpoint is durable
    and auto-resumable, and the trainer still reports a clean
    preemption (the exit-18 contract)."""
    child = _CHILD.format(
        repo=REPO, providers=PROVIDER_DIR, ws=str(tmp_path),
        fault_spec="checkpoint.write=sleep:2@2", cfg=_CHILD_CFG, init="",
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", child], cwd=tmp_path,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # wait for the first save to be enqueued (pass-00000 write begins;
    # its 2nd file write sleeps 2s — the window), then preempt
    deadline = time.monotonic() + 120
    tmp_seen = False
    out = str(tmp_path / "out")
    while time.monotonic() < deadline:
        if os.path.isdir(out) and any(
            d.startswith("pass-") for d in os.listdir(out)
        ):
            tmp_seen = True
            break
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    assert tmp_seen, "first checkpoint write never started"
    proc.send_signal(signal.SIGTERM)
    stdout, _ = proc.communicate(timeout=180)
    assert "TRAIN_DONE" in stdout, stdout
    path = ckpt.find_restorable_checkpoint(out)
    assert path is not None and ckpt.verify_checkpoint(path) == []
    r2 = _run_child(tmp_path, init="auto")
    assert "TRAIN_DONE" in r2.stdout, r2.stdout + r2.stderr
