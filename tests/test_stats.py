"""utils/stats.py direct coverage (Stat, StatSet, stat_timer nesting and
threading) and the streaming-histogram quantile math pinned against
numpy percentiles."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.observability.metrics import Histogram
from paddle_tpu.utils.stats import Stat, StatSet, global_stats, stat_timer

pytestmark = pytest.mark.obs


# ------------------------------------------------------------- Stat(Set)


def test_stat_accumulates_total_count_max_avg():
    s = Stat("x")
    for dt in (0.1, 0.3, 0.2):
        s.add(dt)
    assert s.count == 3
    assert s.total_s == pytest.approx(0.6)
    assert s.max_s == pytest.approx(0.3)
    assert s.avg_s == pytest.approx(0.2)
    # empty stat: avg must not divide by zero
    assert Stat("y").avg_s == 0.0


def test_statset_get_is_stable_and_summary_sorts_by_total():
    ss = StatSet("t")
    assert ss.get("a") is ss.get("a")
    ss.get("small").add(0.001)
    ss.get("big").add(1.0)
    text = ss.summary()
    assert text.index("big") < text.index("small")
    assert "n=1" in text
    ss.reset()
    assert "empty" in ss.summary()


def test_statset_threaded_adds_lose_nothing():
    ss = StatSet("threads")
    N, T = 200, 8

    def work():
        for _ in range(N):
            ss.get("shared").add(0.001)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = ss.get("shared")
    assert st.count == N * T
    assert st.total_s == pytest.approx(0.001 * N * T)


# ------------------------------------------------------------ stat_timer


def test_stat_timer_records_scope_and_nests():
    global_stats.reset()
    with stat_timer("outer_scope"):
        time.sleep(0.01)
        with stat_timer("inner_scope"):
            time.sleep(0.01)
    outer = global_stats.get("outer_scope")
    inner = global_stats.get("inner_scope")
    assert outer.count == 1 and inner.count == 1
    # the outer scope contains the inner one
    assert outer.total_s >= inner.total_s
    assert inner.total_s >= 0.005


def test_stat_timer_concurrent_threads_each_count():
    global_stats.reset()
    T = 4

    def work(i):
        with stat_timer("thread_scope"):
            time.sleep(0.005)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert global_stats.get("thread_scope").count == T


# ------------------------------------------------------------- Histogram


@pytest.mark.parametrize("dist", ["uniform", "lognormal", "constant"])
def test_histogram_quantiles_match_numpy(dist):
    rng = np.random.RandomState(0)
    if dist == "uniform":
        xs = rng.uniform(0.001, 2.0, size=5000)
    elif dist == "lognormal":
        xs = rng.lognormal(mean=-2.0, sigma=1.0, size=5000)
    else:
        xs = np.full(1000, 0.25)
    h = Histogram("t", growth=1.05)
    for x in xs:
        h.observe(float(x))
    for q in (0.5, 0.9, 0.99):
        want = float(np.percentile(xs, q * 100))
        got = h.quantile(q)
        # geometric buckets: relative error bounded by the bucket width
        assert got == pytest.approx(want, rel=0.08), (dist, q, got, want)
    assert h.count == len(xs)
    assert h.mean == pytest.approx(float(xs.mean()), rel=1e-6)
    snap = h.snapshot()
    assert snap["count"] == len(xs)
    assert snap["max"] == pytest.approx(float(xs.max()))


def test_histogram_edge_cases():
    h = Histogram("e")
    assert h.quantile(0.5) == 0.0  # empty
    h.observe(0.0)     # underflow bucket
    h.observe(-1.0)    # negative clamps to min_value
    assert h.quantile(0.5) <= h.min_value
    # quantiles never report outside the observed range
    h2 = Histogram("e2")
    h2.observe(3.0)
    assert h2.quantile(0.99) == pytest.approx(3.0)
    assert h2.quantile(0.0) <= 3.0
