"""Native datapath: the C++ scanners must byte-match the NumPy fallback.

Analog of the reference's CPU↔GPU kernel-equivalence tests
(test_matrixCompare.cpp pattern, SURVEY.md §4): same batch packed by the
native library and by the pure-NumPy path must be identical.
"""

import random

import numpy as np
import pytest

from paddle_tpu.data.feeder import BatchAssembler
from paddle_tpu.data.provider import (
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_binary_vector_sequence,
    sparse_value_slot,
    sparse_vector_sequence,
)
from paddle_tpu.native import get_lib


def _assemblers(input_types, names):
    a_native = BatchAssembler(input_types, names)
    a_py = BatchAssembler(input_types, names)
    a_py._native = None
    if a_native._native is None:
        pytest.skip("native datapath unavailable")
    return a_native, a_py


def _check(a_native, a_py, samples, names):
    out_n = a_native.assemble(samples)
    out_p = a_py.assemble(samples)
    for name in names:
        n, p = out_n[name], out_p[name]
        for field in ("value", "ids", "seq_lengths", "sub_seq_lengths"):
            fn, fp = getattr(n, field), getattr(p, field)
            assert (fn is None) == (fp is None), (name, field)
            if fn is not None:
                np.testing.assert_array_equal(np.asarray(fn), np.asarray(fp),
                                              err_msg=f"{name}.{field}")


def test_native_lib_builds():
    lib = get_lib()
    if lib is None:
        pytest.skip("no toolchain")
    assert lib.pt_datapath_abi_version() == 1


def test_index_and_sparse_slots_match():
    rng = random.Random(7)
    types = [
        integer_value_sequence(50),
        sparse_binary_vector(40),
        sparse_value_slot(30),
        integer_value(9),
    ]
    names = ["seq", "bow", "sv", "label"]
    a_n, a_p = _assemblers(types, names)
    samples = []
    for _ in range(17):
        seq = [rng.randrange(50) for _ in range(rng.randint(1, 23))]
        bow = sorted(rng.sample(range(40), rng.randint(0, 10)))
        sv = [(i, rng.random()) for i in sorted(rng.sample(range(30), 4))]
        samples.append([seq, bow, sv, rng.randrange(9)])
    _check(a_n, a_p, samples, names)


def test_dense_and_sparse_sequences_match():
    rng = random.Random(11)
    types = [
        dense_vector_sequence(8),
        sparse_binary_vector_sequence(25),
        sparse_vector_sequence(15),
    ]
    names = ["dv", "sbs", "svs"]
    a_n, a_p = _assemblers(types, names)
    samples = []
    for _ in range(9):
        n = rng.randint(1, 12)
        dv = [[rng.random() for _ in range(8)] for _ in range(n)]
        sbs = [sorted(rng.sample(range(25), rng.randint(0, 5))) for _ in range(n)]
        svs = [
            [(i, rng.random()) for i in sorted(rng.sample(range(15), rng.randint(0, 4)))]
            for _ in range(n)
        ]
        samples.append([dv, sbs, svs])
    _check(a_n, a_p, samples, names)


def test_out_of_range_sparse_index_raises():
    types = [sparse_binary_vector(10)]
    a_n, _ = _assemblers(types, ["bow"])
    with pytest.raises(IndexError):
        a_n.assemble([[[3, 10]]])
    with pytest.raises(IndexError):
        a_n.assemble([[[-1, 2]]])


def test_nested_index_sequences_match():
    rng = random.Random(13)
    types = [integer_value_sub_sequence(60)]
    names = ["nested"]
    a_n, a_p = _assemblers(types, names)
    samples = []
    for _ in range(7):
        subs = [
            [rng.randrange(60) for _ in range(rng.randint(1, 9))]
            for _ in range(rng.randint(1, 5))
        ]
        samples.append([subs])
    _check(a_n, a_p, samples, names)
