"""Hang defense (doc/resilience.md "Hang detection & elastic relaunch"):
the in-process step-progress hangwatch behind ``--step_hang_timeout``,
the heartbeat liveness layer, the 17/18/19 exit-code discipline, and
the supervisor's preemption/hang handling.

Unit tests drive the watchdog and the staleness logic with fake clocks
(no sleeping); the chaos e2e proves the acceptance scenario with a REAL
wedged trainer: an injected ``trainer.stall`` is detected within
``--step_hang_timeout``, leaves a ``hang_report.json`` with all thread
stacks, exits 19, and ``paddle supervise`` restarts the run to
completion.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.resilience import (
    EXIT_CRASH_LOOP,
    EXIT_HANG,
    EXIT_PREEMPTED,
    faultinject,
    heartbeat as hb,
)
from paddle_tpu.resilience.hangwatch import HANG_REPORT, HangWatch
from paddle_tpu.resilience.supervisor import CRASH_REPORT, Supervisor
from paddle_tpu.utils.flags import _Flags, flag_value

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDERS = os.path.join(REPO, "tests", "providers")

SUBPROC_ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    PALLAS_AXON_POOL_IPS="",
    PYTHONPATH=f"{REPO}:{os.path.join(REPO, 'compat')}:{PROVIDERS}",
)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faultinject.configure("")


# ----------------------------------------------------------- exit codes


def test_exit_codes_are_distinct_and_stable():
    """Wrappers dispatch on these; they may never collide or drift."""
    assert (EXIT_CRASH_LOOP, EXIT_PREEMPTED, EXIT_HANG) == (17, 18, 19)
    assert len({EXIT_CRASH_LOOP, EXIT_PREEMPTED, EXIT_HANG}) == 3
    # the supervisor re-exports the crash-loop code for old importers
    from paddle_tpu.resilience import supervisor

    assert supervisor.EXIT_CRASH_LOOP == EXIT_CRASH_LOOP


# ------------------------------------------------------------ hangwatch


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _watch(tmp_path, timeout=10.0):
    clock = _FakeClock()
    fired = []
    hw = HangWatch(
        timeout, report_dir=str(tmp_path),
        clock=clock, exit_fn=fired.append,
    )
    return hw, clock, fired


def test_hangwatch_fires_only_past_timeout(tmp_path):
    hw, clock, fired = _watch(tmp_path)
    hw.ping(0, 3)
    clock.t = 9.0
    assert hw.check() == pytest.approx(9.0)
    assert fired == []
    # a ping resets the age — a progressing loop never fires
    hw.ping(0, 4)
    clock.t = 18.0
    hw.check()
    assert fired == []
    clock.t = 30.0
    hw.check()
    assert fired == [EXIT_HANG]
    # the report landed atomically (no .tmp left behind)
    assert os.path.exists(tmp_path / HANG_REPORT)
    assert not os.path.exists(str(tmp_path / HANG_REPORT) + ".tmp")


def test_hangwatch_report_carries_stacks_and_context(tmp_path):
    hw, clock, fired = _watch(tmp_path, timeout=5.0)
    # give the report a metrics tail to pick up
    from paddle_tpu.observability import metrics as obs

    obs.configure(str(tmp_path))
    obs.emit("pass_end", pass_id=1, step=7)
    obs.flush()
    try:
        hw.ping(1, 7)
        clock.t = 6.0
        hw.check()
    finally:
        obs.configure("")
    assert fired == [EXIT_HANG]
    report = json.load(open(tmp_path / HANG_REPORT))
    assert report["reason"] == "step_hang"
    assert report["timeout_s"] == 5.0
    assert report["last_progress"] == {"pass": 1, "step": 7}
    # every thread's stack, with file:line frames — this test's own
    # frame must be visible in the main thread's stack
    assert report["threads"], report
    all_frames = "\n".join(
        f for t in report["threads"].values() for f in t["frames"]
    )
    assert "test_hangwatch.py" in all_frames
    # telemetry tail rode along
    kinds = [r["kind"] for r in report["metrics_tail"]["0"]]
    assert "pass_end" in kinds


def test_hangwatch_gauge_and_max_age(tmp_path):
    from paddle_tpu.observability import metrics as obs

    hw, clock, _fired = _watch(tmp_path, timeout=100.0)
    hw.ping()
    clock.t = 7.0
    hw.check()
    assert obs.registry().gauge("trainer.progress_age_s").value == 7.0
    clock.t = 9.0
    hw.check()
    hw.ping()
    clock.t = 10.0
    hw.check()
    # max since construction, then reset (the trainer reads this once
    # per pass into the pass_end record)
    assert hw.take_max_age() == pytest.approx(9.0)
    assert hw.take_max_age() == pytest.approx(0.0)
    # a stall SHORTER than the monitor poll period still registers:
    # ping() folds the age it just ended into the max, so a near-miss
    # the monitor thread never sampled reaches progress_age_max_s
    clock.t = 14.0
    hw.ping()  # 5s since the ping at t=9, never sampled by check()
    assert hw.take_max_age() == pytest.approx(5.0)


def test_hangwatch_thread_detects_real_stall(tmp_path):
    """The actual monitor thread (real clock, tiny timeout): no pings →
    fires within a few poll periods; exit_fn is captured, not os._exit."""
    fired = []
    hw = HangWatch(0.2, report_dir=str(tmp_path), exit_fn=fired.append)
    hw.start()
    try:
        deadline = time.monotonic() + 10
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        hw.stop()
    assert fired == [EXIT_HANG]
    assert os.path.exists(tmp_path / HANG_REPORT)


# ------------------------------------------------------------ heartbeat


def test_heartbeat_staleness_with_fake_clock(tmp_path):
    d = str(tmp_path)
    hb.write_beat(d, 0, clock=lambda: 100.0)
    hb.write_beat(d, 1, clock=lambda: 107.0)
    assert hb.stale_hosts(d, 2, 10.0, now=108.0) == []
    # only host 0 has gone silent past the threshold
    assert hb.stale_hosts(d, 2, 10.0, now=112.0) == [(0, 12.0)]
    stale = dict(hb.stale_hosts(d, 2, 10.0, now=150.0))
    assert stale == {0: 50.0, 1: 43.0}


def test_heartbeat_never_started_host_aged_from_epoch(tmp_path):
    d = str(tmp_path)
    hb.write_beat(d, 0, clock=lambda: 100.0)
    # host 1 never wrote a beat: judged from the observation epoch, so a
    # trainer wedged before its FIRST beat is still caught — but only
    # after the startup grace (since + stale_after)
    assert hb.stale_hosts(d, 2, 10.0, now=105.0, since=100.0) == []
    assert (1, 20.0) in hb.stale_hosts(d, 2, 10.0, now=120.0, since=100.0)
    # without an epoch a missing beat is unjudgeable
    assert hb.stale_hosts(d, 2, 10.0, now=120.0) == [(0, 20.0)]


def test_heartbeat_epoch_clamps_previous_round(tmp_path):
    """Beats from before a relaunch must not instantly re-flag a host:
    ages are clamped to the new round's start."""
    d = str(tmp_path)
    hb.write_beat(d, 0, clock=lambda: 100.0)
    assert hb.stale_hosts(d, 1, 10.0, now=200.0, since=195.0) == []
    assert hb.stale_hosts(d, 1, 10.0, now=210.0, since=195.0) == [(0, 15.0)]


def test_heartbeat_writer_renews_and_marks_stop(tmp_path):
    d = str(tmp_path)
    w = hb.HeartbeatWriter(d, 3, 0.05)
    w.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            beat = hb.read_beats(d).get(3)
            if beat and beat["seq"] >= 3:
                break
            time.sleep(0.02)
    finally:
        w.stop()
    final = hb.read_beats(d)[3]
    assert final["seq"] >= 3
    assert final.get("stopped") is True  # clean exit is distinguishable
    assert final["interval_s"] == 0.05
    # torn beats are impossible (atomic replace): no tmp litter
    assert not [n for n in os.listdir(d) if ".tmp." in n]


def test_heartbeat_ignores_garbage_files(tmp_path):
    d = str(tmp_path)
    (tmp_path / "host-9.json").write_text("{not json")
    (tmp_path / "unrelated.txt").write_text("x")
    hb.write_beat(d, 0, clock=lambda: 50.0)
    assert set(hb.read_beats(d)) == {0}


def test_resolve_dir_precedence(tmp_path):
    assert hb.resolve_dir("/explicit", "/save") == "/explicit"
    assert hb.resolve_dir("", "/save") == os.path.join("/save", "heartbeats")
    assert hb.resolve_dir("", "") == ""


def test_run_dir_of_handles_jsonl_metrics_path():
    """--metrics_path may be an explicit *.jsonl stream file (a shape
    metrics.py supports); the hang report and the supervisor looking
    for it must both land on the containing directory."""
    from paddle_tpu.resilience.hangwatch import run_dir_of

    assert run_dir_of("/runs/a") == "/runs/a"
    assert run_dir_of("/runs/a/metrics.jsonl") == "/runs/a"
    assert run_dir_of("metrics.jsonl") == "."


# --------------------------------------------------- flag_value helper


def test_flag_value_reads_both_forms_last_wins():
    argv = ["--a=1", "--heartbeat_interval", "2", "--b",
            "--heartbeat_interval=5"]
    assert flag_value(argv, "heartbeat_interval") == "5"
    assert flag_value(argv, "missing", "dflt") == "dflt"
    # prefix must not match a longer flag name
    assert flag_value(["--heartbeat_interval_x=9"], "heartbeat_interval") == ""


# -------------------------------------------------------- paddle faults


def test_paddle_faults_lists_every_site(capsys):
    from paddle_tpu import cli
    from paddle_tpu.resilience.faultinject import KNOWN_SITES, SITE_DOCS

    assert cli.main(["faults"]) == 0
    out = capsys.readouterr().out
    for site in KNOWN_SITES:
        assert site in out, site
    assert "trainer.stall" in SITE_DOCS
    # the doc page points at the same table
    doc = open(os.path.join(REPO, "doc", "resilience.md")).read()
    assert "paddle faults" in doc
    for site in KNOWN_SITES:
        assert site in doc, f"{site} undocumented in doc/resilience.md"


# ------------------------------------------- supervisor exit-code rules


def _stub_supervisor(tmp_path, script, flags=None, **kw):
    flags = flags or _Flags(
        supervise_dir=str(tmp_path / "sup"),
        restart_budget=5,
        crash_loop_threshold=3,
    )
    return Supervisor(
        ["--config=unused.py"], flags,
        child_cmd=[sys.executable, "-c", script, str(tmp_path / "counter")],
        sleep=lambda _s: None, **kw,
    )


def test_supervisor_preemption_exit_is_a_free_restart(tmp_path):
    """A child exiting EXIT_PREEMPTED is restarted even with ZERO
    restart budget — preemption is the scheduler's decision, not a
    failure — and the preempted attempt never feeds crash-loop
    accounting."""
    script = textwrap.dedent(f"""
        import os, sys
        c = sys.argv[1]
        n = int(open(c).read()) if os.path.exists(c) else 0
        open(c, "w").write(str(n + 1))
        sys.exit({EXIT_PREEMPTED} if n < 2 else 0)
    """)
    flags = _Flags(
        supervise_dir=str(tmp_path / "sup"),
        restart_budget=0,           # no budget at all
        crash_loop_threshold=2,     # two same-state deaths would stop it
    )
    sup = _stub_supervisor(tmp_path, script, flags=flags)
    assert sup.run() == 0
    codes = [a["exit_code"] for a in sup.attempts]
    assert codes == [EXIT_PREEMPTED, EXIT_PREEMPTED, 0]
    assert not os.path.exists(os.path.join(sup.dir, CRASH_REPORT))


def test_supervisor_hang_exit_consumes_budget_and_attaches_report(tmp_path):
    """EXIT_HANG is a real failure: it consumes budget, and the crash
    report embeds the child's hang_report.json forensics."""
    metrics_dir = tmp_path / "run"
    metrics_dir.mkdir()
    hang = {"reason": "step_hang", "age_s": 42.0,
            "threads": {"MainThread": {"daemon": False, "frames": ["f:1"]}}}
    flags = _Flags(
        supervise_dir=str(tmp_path / "sup"),
        metrics_path=str(metrics_dir),
        restart_budget=1,
        crash_loop_threshold=10,
    )
    # keeps "progressing" so this is budget exhaustion, not a crash loop
    progress = iter(range(100))
    sup = _stub_supervisor(
        tmp_path, f"import sys; sys.exit({EXIT_HANG})", flags=flags,
        probe=lambda: f"pass-{next(progress):05d}",
    )
    # written AFTER the supervisor was born, as the real hangwatch would
    (metrics_dir / HANG_REPORT).write_text(json.dumps(hang))
    assert sup.run() == EXIT_HANG
    assert [a["exit_code"] for a in sup.attempts] == [EXIT_HANG, EXIT_HANG]
    report = json.load(open(os.path.join(sup.dir, CRASH_REPORT)))
    assert report["reason"] == "restart_budget_exhausted"
    assert report["hang_report"]["age_s"] == 42.0
    assert report["hang_report"]["threads"]

    # a hang_report.json predating the supervise run (leftover from an
    # earlier incident in the same save_dir) must NOT be embedded as
    # this run's forensics
    old = time.time() - 3600
    os.utime(metrics_dir / HANG_REPORT, (old, old))
    sup2 = _stub_supervisor(
        tmp_path, f"import sys; sys.exit({EXIT_HANG})", flags=flags,
        probe=lambda: f"pass-{next(progress):05d}",
    )
    assert sup2.run() == EXIT_HANG
    report2 = json.load(open(os.path.join(sup2.dir, CRASH_REPORT)))
    assert report2["hang_report"] is None


# --------------------------------------------- end-to-end (subprocess)


def _write_train_cfg(tmp_path):
    (tmp_path / "train.list").write_text("1\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={str(tmp_path / 'train.list')!r},
                            test_list=None,
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02,
             learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg = tmp_path / "cfg.py"
    cfg.write_text(src)
    return str(cfg)


@pytest.mark.chaos
def test_supervise_e2e_hang_detected_reported_and_recovered(tmp_path):
    """The acceptance scenario end-to-end: a deliberately stalled
    trainer (`trainer.stall` sleep at launch 18 = pass 2, batch 3) is
    detected within --step_hang_timeout, leaves hang_report.json with
    thread stacks, exits 19, and `paddle supervise` restarts it from
    the pass-1 checkpoint to completion."""
    cfg = _write_train_cfg(tmp_path)
    save_dir = str(tmp_path / "out")
    sup_dir = str(tmp_path / "sup")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         f"--config={cfg}", f"--save_dir={save_dir}",
         f"--supervise_dir={sup_dir}", "--num_passes=3", "--log_period=0",
         # timeout sized for a LOADED 2-CPU container: jit compile of the
         # first launch can legitimately take several seconds, and a
         # false positive here turns the drill into a crash loop
         "--restart_base_delay=0.01", "--step_hang_timeout=10",
         "--fault_spec=trainer.stall=sleep:600@18"],
        capture_output=True, text=True, timeout=420, env=SUBPROC_ENV,
        cwd=str(tmp_path),
    )
    assert r.returncode == 0, (r.returncode, r.stderr[-3000:])
    # the run completed across the hang restart
    assert os.path.isdir(os.path.join(save_dir, "pass-00002"))
    # the hung attempt exited with the distinct hang code and the
    # supervisor named it
    assert f"rc={EXIT_HANG}" in r.stderr and "hang" in r.stderr
    # forensics: all thread stacks, with the stall site on the main one
    report = json.load(open(os.path.join(save_dir, HANG_REPORT)))
    assert report["reason"] == "step_hang"
    all_frames = "\n".join(
        f for t in report["threads"].values() for f in t["frames"]
    )
    assert "faultinject" in all_frames  # the injected sleep is visible
    # the hang record was flushed to telemetry BEFORE the death
    from paddle_tpu.observability import metrics as obs_mod

    kinds = [rec["kind"]
             for recs in obs_mod.read_tail(save_dir, n=200).values()
             for rec in recs]
    assert "hang" in kinds
    # ... and `paddle metrics` warns about it
    r2 = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "metrics", save_dir],
        capture_output=True, text=True, timeout=120, env=SUBPROC_ENV,
    )
    assert r2.returncode == 0, r2.stderr
    assert "hang detected" in r2.stdout
    assert "age s" in r2.stdout  # the per-pass max progress-age column


@pytest.mark.chaos
def test_train_preemption_exits_18(tmp_path):
    """SIGTERM to a bare `paddle train` checkpoints at the launch
    boundary and exits EXIT_PREEMPTED — the distinct code wrappers
    treat as budget-free."""
    cfg = _write_train_cfg(tmp_path)
    save_dir = str(tmp_path / "out")
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.cli", "train",
         f"--config={cfg}", f"--save_dir={save_dir}",
         "--num_passes=500", "--log_period=0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=SUBPROC_ENV, cwd=str(tmp_path),
    )
    try:
        # wait until training is demonstrably under way (pass 0 saved)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if os.path.exists(os.path.join(save_dir, "pass-00000",
                                           "meta.json")):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        assert proc.poll() is None, proc.stdout.read().decode()[-3000:]
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode == EXIT_PREEMPTED, (
        proc.returncode, out.decode()[-3000:]
    )
    assert b"preemption" in out
