"""Memory observability (doc/observability.md "Memory & numerics
telemetry"): static per-launch-group plans on compile records,
pass-boundary live sampling (host-RSS-only degradation on the CPU
backend), the `paddle memory` analyzer, the OOM pre-mortem
(oom_report.json + EXIT_OOM=20) driven by the `trainer.oom` fault site,
and the supervisor's budget-consuming treatment of OOM deaths."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu.observability import memory as obs_mem
from paddle_tpu.observability import metrics as obs
from paddle_tpu.resilience import EXIT_OOM

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROVIDER_DIR = os.path.join(os.path.dirname(__file__), "providers")
SUBPROC_ENV = {
    **os.environ,
    "PYTHONPATH": f"{REPO}:{REPO}/compat:{PROVIDER_DIR}",
    "JAX_PLATFORMS": "cpu",
}


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


def _write_config(tmp_path):
    train_list = tmp_path / "train.list"
    train_list.write_text("1\n2\n")
    test_list = tmp_path / "test.list"
    test_list.write_text("99\n")
    src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *

    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(test_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=64, learning_rate=0.02, learning_method=AdamOptimizer())
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
    """)
    cfg_path = tmp_path / "cfg.py"
    cfg_path.write_text(src)
    return str(cfg_path)


def _records(run_dir):
    out = []
    for path in obs.metrics_files(str(run_dir)):
        out.extend(obs.read_records(path))
    return out


# ------------------------------------------------------------------ units


def test_is_oom_error_narrow():
    assert obs_mem.is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert obs_mem.is_oom_error(RuntimeError("Resource exhausted: ..."))
    assert obs_mem.is_oom_error(MemoryError("out of memory"))
    assert obs_mem.is_oom_error(RuntimeError("failed to allocate 2GB"))
    assert obs_mem.is_oom_error(obs_mem.SyntheticOomError("drill"))
    # a shape bug must crash loudly, never classify as OOM
    assert not obs_mem.is_oom_error(ValueError("shape mismatch [3] vs [4]"))
    assert not obs_mem.is_oom_error(RuntimeError("kaboom"))


def test_memory_analysis_of_graceful():
    class Plan:
        argument_size_in_bytes = 100
        output_size_in_bytes = 40
        temp_size_in_bytes = 60
        alias_size_in_bytes = 30
        generated_code_size_in_bytes = 10

    class Ok:
        def memory_analysis(self):
            return Plan()

    out = obs_mem.memory_analysis_of(Ok())
    assert out["mem_arg_bytes"] == 100
    # arg + out + temp + code - alias
    assert out["mem_total_bytes"] == 100 + 40 + 60 + 10 - 30

    class Raising:
        def memory_analysis(self):
            raise RuntimeError("unimplemented on this backend")

    assert obs_mem.memory_analysis_of(Raising()) is None

    class Empty:
        def memory_analysis(self):
            return object()  # no size attributes at all

    assert obs_mem.memory_analysis_of(Empty()) is None


def test_device_stats_none_degrades_to_host_only(monkeypatch):
    """The CPU backend's memory_stats() is None — and any backend may
    raise; both degrade to a host-RSS-only snapshot that still
    validates (tier-1 runs entirely on this path)."""

    class NoneDev:
        def memory_stats(self):
            return None

    class RaisingDev:
        def memory_stats(self):
            raise RuntimeError("no allocator stats")

    import jax

    for dev in (NoneDev(), RaisingDev()):
        monkeypatch.setattr(jax, "local_devices", lambda d=dev: [d])
        assert obs_mem.device_memory_stats() is None
        snap = obs_mem.sample_memory()
        assert snap["host_rss_bytes"] > 0
        assert "hbm_peak_bytes" not in snap


def test_device_stats_summed_over_devices(monkeypatch):
    class Dev:
        def __init__(self, n):
            self.n = n

        def memory_stats(self):
            return {"bytes_in_use": 10 * self.n,
                    "peak_bytes_in_use": 20 * self.n,
                    "bytes_limit": 100}

    import jax

    monkeypatch.setattr(jax, "local_devices", lambda: [Dev(1), Dev(2)])
    stats = obs_mem.device_memory_stats()
    assert stats == {"bytes_in_use": 30, "peak_bytes_in_use": 60,
                     "devices": 2, "bytes_limit": 200}
    snap = obs_mem.sample_memory()
    assert snap["hbm_peak_bytes"] == 60 and snap["hbm_limit_bytes"] == 200


def test_sample_and_emit_record_validates(tmp_path):
    obs.configure(str(tmp_path))
    snap = obs_mem.sample_and_emit(pass_id=3, step=7)
    assert snap["host_rss_bytes"] > 0
    obs.flush()
    recs = [r for r in _records(tmp_path) if r["kind"] == "memory"]
    assert len(recs) == 1
    assert obs.validate_record(recs[0]) == []
    assert recs[0]["pass"] == 3 and recs[0]["step"] == 7
    # the gauges ride the registry for the next pass_end snapshot
    assert obs.registry().snapshot()["mem.host_rss_bytes"] > 0


def test_trigger_oom_report_backstop_not_fired(tmp_path):
    """Healthy path: report written + kind=oom record flushed, and the
    forensics backstop timer is cancelled (exit_fn never called)."""
    obs.configure(str(tmp_path))
    exits = []
    err = obs_mem.SyntheticOomError("unit")
    path = obs_mem.trigger_oom_report(
        str(tmp_path), err,
        groups=[{"group": "train_step", "sig": "ab", "mem_total_bytes": 512},
                {"group": "test_fwd", "sig": "cd", "mem_total_bytes": 1024}],
        live={"host_rss_bytes": 123},
        where={"pass": 1, "step": 5},
        exit_fn=exits.append,
    )
    assert exits == []  # backstop cancelled on the normal path
    report = json.load(open(path))
    assert report["reason"] == "oom"
    # ranked: the biggest plan leads
    assert [g["group"] for g in report["groups"]] == ["test_fwd", "train_step"]
    assert report["static_total_bytes"] == 1536
    assert report["where"] == {"pass": 1, "step": 5}
    assert "metrics_tail" in report
    oom_recs = [r for r in _records(tmp_path) if r["kind"] == "oom"]
    assert len(oom_recs) == 1 and obs.validate_record(oom_recs[0]) == []
    assert oom_recs[0]["report"] == path


# --------------------------------------------------- smoke train (shared)


@pytest.fixture(scope="module")
def smoke_run(tmp_path_factory):
    """One in-process 2-pass smoke train with telemetry on — shared by
    the record-shape tests below (training twice would double the suite
    cost for identical evidence)."""
    tmp_path = tmp_path_factory.mktemp("mem_smoke")
    cfg = _write_config(tmp_path)
    sys.path.insert(0, PROVIDER_DIR)
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    save_dir = str(tmp_path / "out")
    FLAGS.config = cfg
    FLAGS.save_dir = save_dir
    FLAGS.num_passes = 2
    FLAGS.log_period = 0
    FLAGS.start_pass = 0
    FLAGS.init_model_path = ""
    FLAGS.seed = 7
    FLAGS.metrics_path = ""
    FLAGS.numerics_log_period = 0
    obs.registry().reset()
    try:
        trainer = Trainer(parse_config(cfg, ""), FLAGS)
        trainer.train()
    finally:
        obs.configure("")
        sys.path.remove(PROVIDER_DIR)
    return save_dir, _records(save_dir)


def test_smoke_compile_records_carry_static_plan(smoke_run):
    _save_dir, recs = smoke_run
    compiles = [r for r in recs if r["kind"] == "compile"]
    assert compiles, "no compile records in the smoke run"
    with_mem = [c for c in compiles if "mem_total_bytes" in c]
    assert with_mem, "no compile record carries the static memory plan"
    for c in with_mem:
        assert obs.validate_record(c) == []
        assert c["mem_total_bytes"] >= 0
        assert c["mem_arg_bytes"] > 0  # params alone are nonzero


def test_smoke_memory_records_per_pass(smoke_run):
    _save_dir, recs = smoke_run
    mems = [r for r in recs if r["kind"] == "memory"]
    assert {m["pass"] for m in mems} == {0, 1}  # one per pass boundary
    for m in mems:
        assert obs.validate_record(m) == []
        assert m["host_rss_bytes"] > 0
        # CPU backend: allocator stats unavailable — degraded, not broken
        assert "hbm_peak_bytes" not in m
    # the gauges rode the pass_end counters snapshot
    pass_ends = [r for r in recs if r["kind"] == "pass_end"]
    assert pass_ends and all(
        (p.get("counters") or {}).get("mem.host_rss_bytes", 0) > 0
        for p in pass_ends
    )


def test_paddle_memory_renders_smoke_run(smoke_run):
    """`paddle memory <run_dir>` is jax-free: run it in a subprocess
    with jax import poisoned to prove it."""
    save_dir, _recs = smoke_run
    code = (
        "import sys; sys.modules['jax'] = None\n"
        "from paddle_tpu.observability.memory import main\n"
        f"sys.exit(main([{save_dir!r}]))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=SUBPROC_ENV,
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "static footprint per launch group" in out.stdout
    assert "train_step" in out.stdout
    assert "device stats unavailable" in out.stdout  # CPU degradation
    # and --json round-trips
    doc = json.loads(subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "memory", save_dir,
         "--json"],
        env=SUBPROC_ENV, capture_output=True, text=True, timeout=60,
    ).stdout)
    assert doc["groups"] and doc["static_total_bytes"] > 0
    assert "0" in doc["live"] or 0 in doc["live"]


def test_paddle_memory_golden_headroom_table(tmp_path, capsys):
    """Synthetic TPU-shaped stream: static rows ranked, live peak with
    headroom computed against the capacity table for the device kind
    the roofline records name (no allocator limit in the records)."""
    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("compile", group="train_step", sig="aaaa", mem_arg_bytes=10 ** 9,
           mem_out_bytes=10 ** 9, mem_temp_bytes=2 * 10 ** 9,
           mem_total_bytes=4 * 10 ** 9)
    w.emit("compile", group="test_fwd", sig="bbbb", mem_arg_bytes=10 ** 8,
           mem_out_bytes=10 ** 8, mem_temp_bytes=0,
           mem_total_bytes=2 * 10 ** 8)
    w.emit("roofline", group="train_step", sig="aaaa", launches=3,
           exec_s=1.0, device_kind="TPU v4")
    w.emit("memory", host_rss_bytes=10 ** 9, hbm_in_use_bytes=5 * 10 ** 9,
           hbm_peak_bytes=8 * 10 ** 9, devices=1)
    w.close()
    assert obs_mem.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    # ranked: train_step (4 GB) before test_fwd (0.2 GB)
    assert lines.index(
        next(l for l in lines if l.startswith("train_step"))
    ) < lines.index(next(l for l in lines if l.startswith("test_fwd")))
    assert "static total: 4200.00 MB over 2 group(s)" in out
    # v4 capacity 32 GB, peak 8 GB -> 25.0%, headroom 24 GB
    assert "hbm peak 8.00 GB" in out
    assert "capacity 32.00 GB" in out and "peak 25.0%" in out
    assert "headroom 24.00 GB" in out


def test_paddle_memory_capacity_scales_by_device_count(tmp_path, capsys):
    """The live records sum peak over local devices, so the capacity
    table fallback must scale by the recorded device count — a 4-chip
    v4 host is 4 x 32 GB, not 32 (which would read >100% used)."""
    w = obs.MetricsWriter(str(tmp_path), host=0)
    w.emit("roofline", group="train_step", sig="aaaa", launches=1,
           exec_s=1.0, device_kind="TPU v4")
    w.emit("memory", host_rss_bytes=10 ** 9, hbm_in_use_bytes=40 * 10 ** 9,
           hbm_peak_bytes=64 * 10 ** 9, devices=4)
    w.close()
    assert obs_mem.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "capacity 128.00 GB" in out  # 4 devices x 32 GB
    assert "peak 50.0%" in out and "headroom 64.00 GB" in out


def test_paddle_memory_no_data(tmp_path):
    assert obs_mem.main([str(tmp_path / "nowhere")]) == 1


# ------------------------------------------------------------- chaos e2e


def test_chaos_oom_exit20_and_premortem(tmp_path):
    """Injected trainer.oom at a launch boundary: `paddle train` exits
    EXIT_OOM=20, oom_report.json carries the ranked static groups + the
    metrics tail, and the flushed kind=oom record survives the death."""
    cfg = _write_config(tmp_path)
    save_dir = str(tmp_path / "out")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "train",
         f"--config={cfg}", f"--save_dir={save_dir}", "--num_passes=1",
         "--fault_spec=trainer.oom=raise@3"],
        env=SUBPROC_ENV, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == EXIT_OOM, (out.returncode, out.stderr[-2000:])
    report = json.load(open(os.path.join(save_dir, obs_mem.OOM_REPORT)))
    assert report["reason"] == "oom"
    assert "RESOURCE_EXHAUSTED" in report["error"]
    assert any(g["group"] == "train_step" for g in report["groups"])
    assert report["static_total_bytes"] > 0
    assert report["metrics_tail"], "telemetry tail missing from pre-mortem"
    recs = _records(save_dir)
    oom_recs = [r for r in recs if r["kind"] == "oom"]
    assert len(oom_recs) == 1 and obs.validate_record(oom_recs[0]) == []
    # the analyzer warns about it
    from paddle_tpu.observability.analyze import analyze, load_run

    doc = analyze(load_run(save_dir))
    assert any("OOM" in w for w in doc["warnings"])
    # and `paddle memory` renders the pre-mortem jax-free
    mem_out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "memory", save_dir],
        env=SUBPROC_ENV, capture_output=True, text=True, timeout=60,
    )
    assert mem_out.returncode == 0
    assert "OOM pre-mortem" in mem_out.stdout


def test_supervise_oom_consumes_budget_and_embeds_premortem(tmp_path):
    """An OOM loop is deterministic poison: `paddle supervise` charges
    each exit-20 death to --restart_budget (never free like exit 18)
    and its final crash report embeds the child's oom_report.json."""
    cfg = _write_config(tmp_path)
    save_dir = str(tmp_path / "out")
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "supervise",
         f"--config={cfg}", f"--save_dir={save_dir}", "--num_passes=1",
         "--restart_budget=1", "--restart_base_delay=0.01",
         "--fault_spec=trainer.oom=raise@2"],
        env=SUBPROC_ENV, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == EXIT_OOM, (out.returncode, out.stderr[-2000:])
    report = json.load(
        open(os.path.join(save_dir, "supervise", "crash_report.json"))
    )
    # budget consumed: exactly budget+1 attempts, every death an OOM
    assert report["reason"] == "restart_budget_exhausted"
    assert [a["exit_code"] for a in report["attempts"]] == [EXIT_OOM] * 2
    assert report.get("oom_report", {}).get("reason") == "oom"
