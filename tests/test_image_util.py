"""Image preprocessing & augmentation (paddle_tpu/utils/image_util.py,
paddle_tpu/ops/perturbation.py, demo/image_classification pipeline).

Pins shapes, determinism-under-seed, and geometric invariants of the
reference-parity helpers (python/paddle/utils/image_util.py:30-101 and
paddle/cuda/src/hl_perturbation_util.cu roles).
"""

import os
import pickle
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.utils import image_util


def test_flip_is_width_mirror_and_involution():
    im = np.arange(2 * 4 * 6, dtype=np.float32).reshape(2, 4, 6)
    f = image_util.flip(im)
    assert f.shape == im.shape
    np.testing.assert_array_equal(f[:, :, 0], im[:, :, -1])
    np.testing.assert_array_equal(image_util.flip(f), im)
    # grayscale HW too
    g = im[0]
    np.testing.assert_array_equal(image_util.flip(g)[:, 0], g[:, -1])


def test_crop_img_center_and_random_modes():
    im = np.random.RandomState(0).rand(3, 8, 8).astype(np.float32)
    # center crop is deterministic and centered
    c = image_util.crop_img(im, 4, color=True, test=True)
    assert c.shape == (3, 4, 4)
    np.testing.assert_array_equal(c, im[:, 2:6, 2:6])
    # train mode: same seed -> same crop; crop content comes from the image
    a = image_util.crop_img(im, 4, test=False, rng=np.random.RandomState(7))
    b = image_util.crop_img(im, 4, test=False, rng=np.random.RandomState(7))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (3, 4, 4)
    # small images are zero-padded up to inner_size (reference semantics)
    small = np.ones((3, 2, 2), np.float32)
    p = image_util.crop_img(small, 4, test=True)
    assert p.shape == (3, 4, 4)
    assert p.sum() == small.sum() and p[0, 0, 0] == 0.0


def test_preprocess_img_subtracts_mean_and_flattens():
    rng = np.random.RandomState(1)
    im = rng.rand(3, 6, 6).astype(np.float32)
    mean = rng.rand(3, 4, 4).astype(np.float32)
    feat = image_util.preprocess_img(im, mean, 4, is_train=False)
    assert feat.shape == (3 * 4 * 4,)
    np.testing.assert_allclose(
        feat.reshape(3, 4, 4), im[:, 1:5, 1:5] - mean, rtol=1e-6
    )


def test_load_meta_roundtrip_npz_and_pickle(tmp_path):
    mean = np.arange(3 * 6 * 6, dtype=np.float32)
    npz_path = tmp_path / "batches.meta"
    with open(npz_path, "wb") as f:
        np.savez(f, data_mean=mean)
    got = image_util.load_meta(str(npz_path), 6, 4)
    assert got.shape == (3, 4, 4)
    np.testing.assert_array_equal(got, mean.reshape(3, 6, 6)[:, 1:5, 1:5])
    # reference cPickle dict format
    pkl_path = tmp_path / "batches.meta.pkl"
    with open(pkl_path, "wb") as f:
        pickle.dump({"data_mean": mean}, f)
    np.testing.assert_array_equal(image_util.load_meta(str(pkl_path), 6, 4), got)


def test_oversample_ten_crops_with_mirrors():
    im = np.random.RandomState(2).rand(8, 8, 3).astype(np.float32)
    crops = image_util.oversample([im], (4, 4))
    assert crops.shape == (10, 4, 4, 3)
    # crop 0 is the top-left corner; crop 5 is its mirror
    np.testing.assert_array_equal(crops[0], im[0:4, 0:4, :])
    np.testing.assert_array_equal(crops[5], crops[0][:, ::-1, :])
    # crop 4 is the center; crop 9 its mirror
    np.testing.assert_array_equal(crops[4], im[2:6, 2:6, :])
    np.testing.assert_array_equal(crops[9], crops[4][:, ::-1, :])


def test_image_transformer_compose():
    hwc = np.random.RandomState(3).rand(5, 5, 3).astype(np.float32)
    t = image_util.ImageTransformer(
        transpose=(2, 0, 1), channel_swap=(2, 1, 0), mean=np.array([1.0, 2.0, 3.0])
    )
    out = t.transformer(hwc)
    assert out.shape == (3, 5, 5)
    np.testing.assert_allclose(out[0], hwc[:, :, 2] - 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[2], hwc[:, :, 0] - 3.0, rtol=1e-6)


def test_perturb_eval_mode_is_center_crop():
    import jax

    from paddle_tpu.ops.perturbation import perturb

    imgs = np.random.RandomState(4).rand(2, 3, 9, 9).astype(np.float32)
    out = perturb(
        jax.numpy.asarray(imgs), jax.random.PRNGKey(0), tgt_size=5, is_train=False
    )
    assert out.shape == (2, 3, 5, 5)
    np.testing.assert_allclose(np.asarray(out), imgs[:, :, 2:7, 2:7], rtol=1e-6)


def test_perturb_train_deterministic_and_padded():
    import jax

    from paddle_tpu.ops.perturbation import perturb

    imgs = np.random.RandomState(5).rand(2, 3, 8, 8).astype(np.float32) + 1.0
    key = jax.random.PRNGKey(42)
    a = perturb(jax.numpy.asarray(imgs), key, tgt_size=6, rotate_angle=30.0,
                scale_ratio=0.4, sampling_rate=2)
    b = perturb(jax.numpy.asarray(imgs), key, tgt_size=6, rotate_angle=30.0,
                scale_ratio=0.4, sampling_rate=2)
    assert a.shape == (4, 3, 6, 6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a target bigger than the source must read pad_value outside
    big = perturb(jax.numpy.asarray(imgs), key, tgt_size=16, is_train=False,
                  pad_value=-7.0)
    assert np.asarray(big).min() == -7.0
    # the source content (all >= 1.0) survives in-bounds
    assert np.asarray(big).max() >= 1.0


def _load_demo_provider():
    demo = os.path.join(REPO, "demo", "image_classification")
    compat = os.path.join(REPO, "compat")
    if compat not in sys.path:  # the provider imports the paddle.* shims
        sys.path.insert(0, compat)
    sys.path.insert(0, demo)
    try:
        import importlib

        import image_provider

        importlib.reload(image_provider)
        return image_provider
    finally:
        sys.path.remove(demo)


def test_demo_provider_augments_in_train_mode_only():
    ip = _load_demo_provider()
    # test mode is fully deterministic: two openings yield identical streams
    s_test = ip.process.init(img_size=32, src_size=36, num_classes=10, is_train=False)
    t1 = [s for _, s in zip(range(4), ip.process.generator_fn(s_test, "f0"))]
    t2 = [s for _, s in zip(range(4), ip.process.generator_fn(s_test, "f0"))]
    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(a["image"], b["image"])
        assert len(a["image"]) == 3 * 32 * 32
    # train mode re-draws crops/flips: same file yields same stream across
    # openings (seeded by file name) but differs from the test-mode stream
    s_train = ip.process.init(img_size=32, src_size=36, num_classes=10, is_train=True)
    r1 = [s for _, s in zip(range(4), ip.process.generator_fn(s_train, "f0"))]
    r2 = [s for _, s in zip(range(4), ip.process.generator_fn(s_train, "f0"))]
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a["image"], b["image"])
    assert any(
        not np.array_equal(a["image"], b["image"]) for a, b in zip(r1, t1)
    ), "train-mode augmentation should perturb the test-mode pipeline"


def test_cifar_converter_roundtrip(tmp_path):
    """prepare_data.py: raw CIFAR python pickles -> batch files + meta;
    the demo provider trains straight off the converted output."""
    sys.path.insert(0, os.path.join(REPO, "demo", "image_classification"))
    try:
        import prepare_data
    finally:
        sys.path.remove(os.path.join(REPO, "demo", "image_classification"))

    # tiny synthetic "CIFAR" fixture in the real pickle format
    raw = tmp_path / "cifar-10-batches-py"
    raw.mkdir()
    rng = np.random.RandomState(0)
    for name, n in [("data_batch_1", 20), ("data_batch_2", 12), ("test_batch", 8)]:
        with open(raw / name, "wb") as f:
            pickle.dump(
                {b"data": rng.randint(0, 256, (n, 3072), dtype=np.uint8),
                 b"labels": [int(x) for x in rng.randint(0, 10, n)]},
                f, protocol=2,
            )
    out = tmp_path / "cifar-out"
    n_train, n_test = prepare_data.convert(str(raw), str(out), samples_per_batch=16)
    assert (n_train, n_test) == (32, 8)

    train_list = (out / "train.list").read_text().strip().splitlines()
    assert len(train_list) == 2  # 32 samples / 16 per batch
    with open(train_list[0], "rb") as f:
        batch = pickle.load(f)
    assert batch["images"].shape == (16, 3, 32, 32)
    assert batch["images"].dtype == np.float32
    assert 0.0 <= batch["images"].min() and batch["images"].max() <= 1.0

    mean = image_util.load_meta(str(out / "batches.meta"), 32, 32)
    assert mean.shape == (3, 32, 32)

    # provider consumes the converted batches end-to-end (real_batches path)
    ip = _load_demo_provider()
    s = ip.process.init(
        img_size=32, src_size=32, num_classes=10,
        meta=str(out / "batches.meta"), is_train=True,
    )
    samples = list(ip.process.generator_fn(s, train_list[0]))
    assert len(samples) == 16
    assert len(samples[0]["image"]) == 3 * 32 * 32
    assert all(0 <= s["label"] < 10 for s in samples)
