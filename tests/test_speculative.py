"""Speculative decode + reduced-precision slot state (PR 20,
doc/serving.md "Speculative decode" / "Reduced-precision slot state"):

- DraftTable / parse_spec_tokens / pick_spec_k units,
- FakeBackend verify-launch semantics (full accept, first-mismatch
  correction, empty-draft plain step, budget/EOS mid-draft),
- exact greedy parity: spec-on == spec-off across the draft ladder,
  BOTH scheduler loops, on seeded ``schedule_requests`` workloads —
  including an adversarial low-acceptance stream (the EMA fallback),
- acceptance-EMA adaptation: collapse turns speculation off per engine
  and per request with ZERO backend reconfiguration, re-probe resumes,
- speculation telemetry: ``note_spec`` counters, ``accept_rate`` on
  the serve_window record, the serve-report accept column,
- ``paddle compare``: accept_rate (zero-filled, higher-is-better) and
  slot_bytes (lower-is-better) join the rung verdict surface,
- the device-modeled A/B: with verify positions cheaper than plain
  micro-steps (batched vocab scoring — the TPU justification, PR-13
  device-modeling precedent), spec-on beats spec-off on goodput at an
  overload rung and `paddle compare` says IMPROVED,
- jax backend: serve_verify parity + one-signature recompiles=0 across
  the K ladder; bf16 slot state token parity within tolerance and ~2x
  slots at fixed memory_analysis arg footprint.
"""

import json

import numpy as np
import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability import serving as slog
from paddle_tpu.serving import (
    DraftTable,
    Engine,
    FakeBackend,
    drive_rung,
    parse_slot_dtype,
    parse_spec_tokens,
    pick_spec_k,
)
from paddle_tpu.serving.engine import (
    SPEC_EMA_FULL,
    SPEC_EMA_OFF,
    SPEC_MIN_SAMPLES,
)
from paddle_tpu.utils import concurrency as cc

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")


# ------------------------------------------------------------------ units


def test_parse_spec_tokens():
    assert parse_spec_tokens(None) == ()
    assert parse_spec_tokens(0) == ()
    assert parse_spec_tokens("0") == ()
    assert parse_spec_tokens("") == ()
    assert parse_spec_tokens(4) == (4,)
    assert parse_spec_tokens("4,2,4") == (2, 4)
    assert parse_spec_tokens([8, 1]) == (1, 8)
    # rungs < 1 drop (not clamp): "0,4" means a 1-rung ladder, not (1, 4)
    assert parse_spec_tokens("0,4") == (4,)


def test_parse_slot_dtype():
    assert parse_slot_dtype(None) == "f32"
    assert parse_slot_dtype("f32") == "f32"
    assert parse_slot_dtype(" BF16 ") == "bf16"
    with pytest.raises(ValueError, match="serve_slot_dtype"):
        parse_slot_dtype("fp8")


def test_plan_slot_dtype_layers_on_fused_plan():
    """The slot-dtype plan is a STORAGE layer: f32 keeps zero tolerance,
    bf16 carries a nonzero parity tolerance, unknown names refuse with
    the reason — and the f32-compute refusal of plan_fused_step is
    untouched (pinned by test_fused_step_refuses_off_template_models)."""
    from paddle_tpu.graph.decode_step import plan_slot_dtype

    p32, why = plan_slot_dtype("f32")
    assert why == "" and p32["store_dtype"] is None
    assert p32["parity_tol"] == 0.0
    p16, why = plan_slot_dtype("bf16")
    assert why == "" and p16["store_dtype"] == "bfloat16"
    assert p16["parity_tol"] > 0.0
    bad, why = plan_slot_dtype("fp8")
    assert bad is None and "fp8" in why


def test_draft_table_learns_and_proposes():
    dt = DraftTable()
    # deterministic period-3 stream: trigram contexts disambiguate it
    seq = [11, 12, 13] * 5
    dt.observe(seq)
    assert dt.propose([11, 12], 3) == [13, 11, 12]
    assert dt.propose([12, 13], 2) == [11, 12]
    # unseen context: no proposal, never a guess
    assert dt.propose([99, 98], 4) == []
    # empty context (stream opening): the most common first token
    assert dt.propose([], 1) == [11]


def test_draft_table_observe_context_no_double_count():
    """observe(tokens, context=...) counts only transitions whose
    successor is inside ``tokens`` — re-observing the boundary with the
    committed context must not double-count interior transitions."""
    dt = DraftTable()
    dt.observe([1, 2, 3])
    n0 = len(dt)
    dt.observe([4], context=[2, 3])  # boundary: (2,3)->4, (3,)->4 only
    assert dt.propose([2, 3], 1) == [4]
    assert len(dt) > n0


def test_draft_table_lru_bound():
    dt = DraftTable(max_contexts=8)
    for i in range(100):
        dt.observe([i, i + 1, i + 2])
    assert len(dt) <= 8


def test_pick_spec_k_policy():
    ladder = (2, 4, 8)
    # unmeasured: probe the bottom rung
    assert pick_spec_k(ladder, 0.0, 0) == 2
    assert pick_spec_k(ladder, 0.0, SPEC_MIN_SAMPLES - 1) == 2
    # collapsed acceptance: plain decode, zero recompiles by construction
    assert pick_spec_k(ladder, SPEC_EMA_OFF - 0.01, 100) == 0
    # confident: the top rung
    assert pick_spec_k(ladder, SPEC_EMA_FULL, 100) == 8
    assert pick_spec_k(ladder, 1.0, 100) == 8
    # in between: monotone interpolation across the ladder
    ks = [pick_spec_k(ladder, e, 100) for e in (0.25, 0.45, 0.7)]
    assert ks == sorted(ks) and all(k in ladder for k in ks)
    assert pick_spec_k((), 1.0, 100) == 0


# ------------------------------------------- FakeBackend verify semantics


def _admitted(be, budgets, rids=None):
    rids = rids or [f"r{i}" for i in range(len(budgets))]
    reqs = [slog.Request(rid=r, t_enqueue=0.0, prompt=[2]) for r in rids]
    be.admit(list(range(len(reqs))), reqs, budgets)
    return reqs


def test_fake_verify_full_accept_and_mismatch():
    # scripted stream: 11, 12, 13, 11, ...
    be = FakeBackend(slots=2, max_length=16, eos=1,
                     token_fn=lambda rid, i: (11, 12, 13)[i % 3],
                     spec_tokens="4")
    _admitted(be, [8, 8])
    # slot 0 drafts the true stream (full accept: exactly K tokens);
    # slot 1 drafts wrong at position 1 (commits draft[0] + correction)
    out = be.step(draft={0: [11, 12, 13, 11], 1: [11, 99, 13, 11]})
    assert be.verify_launches == 1
    t0 = [int(out.tokens[u, 0]) for u in range(4) if out.live[u, 0]]
    t1 = [int(out.tokens[u, 1]) for u in range(4) if out.live[u, 1]]
    assert t0 == [11, 12, 13, 11]       # K accepted
    assert t1 == [11, 12]               # 1 accepted + corrected rides free
    # slot without a draft advances exactly one plain step
    out2 = be.step(draft={0: [12, 13]})
    t1b = [int(out2.tokens[u, 1]) for u in range(out2.tokens.shape[0])
           if out2.live[u, 1]]
    assert t1b == [13]


def test_fake_verify_budget_lands_mid_draft():
    be = FakeBackend(slots=1, max_length=16, eos=1,
                     token_fn=lambda rid, i: (11, 12, 13)[i % 3],
                     spec_tokens="4")
    _admitted(be, [2])
    out = be.step(draft={0: [11, 12, 13, 11]})
    toks = [int(out.tokens[u, 0]) for u in range(4) if out.live[u, 0]]
    assert toks == [11, 12] and bool(out.finished[0])


def test_fake_verify_eos_mid_draft():
    be = FakeBackend(slots=1, max_length=16, eos=12,
                     token_fn=lambda rid, i: (11, 12, 13)[i % 3],
                     spec_tokens="4")
    _admitted(be, [8])
    out = be.step(draft={0: [11, 12, 13]})
    toks = [int(out.tokens[u, 0]) for u in range(out.tokens.shape[0])
            if out.live[u, 0]]
    assert toks == [11, 12] and bool(out.finished[0])


# ------------------------------------------------- engine greedy parity


def _drive(be, pipeline, reqs, rate=50.0):
    eng = Engine(be, request_timeout_s=60.0, pipeline=pipeline).start()
    w = drive_rung(eng, reqs, rate_rps=rate, rung=0)
    assert eng.drain(timeout=60.0)
    return eng, w


def _tokens_of(be, pipeline, reqs):
    eng = Engine(be, request_timeout_s=60.0, pipeline=pipeline).start()
    futs = [eng.submit(r.prompt or [2], max_new_tokens=r.max_new or 6,
                       rid=r.rid) for r in reqs]
    toks = [tuple(f.result(timeout=60.0).tokens) for f in futs]
    assert eng.drain(timeout=60.0)
    return toks, eng


@pytest.mark.parametrize("token_fn,label", [
    (lambda rid, i: (11, 12, 13)[i % 3], "high-acceptance periodic"),
    (lambda rid, i: 2 + (hash((rid, i)) % 97), "adversarial low-acceptance"),
])
def test_spec_parity_on_seeded_workload(token_fn, label):
    """spec-on == spec-off, token for token, across the draft ladder and
    BOTH scheduler loops, on the seeded schedule_requests workload —
    speculation must never change WHAT is generated, only how fast."""
    rng = np.random.RandomState(9)
    reqs = slog.schedule_requests(
        200.0, 12, seed=9,
        prompt_fn=lambda r, i: r.randint(2, 40, size=r.randint(1, 4)).tolist(),
        budget_fn=lambda r, i: 2 + int(r.randint(0, 6)))
    golden = None
    for spec in (None, "2", "2,4"):
        for pipeline in (False, True):
            be = FakeBackend(slots=3, max_length=16, eos=1,
                             token_fn=token_fn, spec_tokens=spec)
            toks, _eng = _tokens_of(be, pipeline, reqs)
            if golden is None:
                golden = toks
            assert toks == golden, (label, spec, pipeline)


def test_spec_parity_under_cancel_timeout_and_fault():
    """The cancel/timeout/fault paths with speculation on: surviving
    requests still match the spec-off stream, faults error the cohort
    exactly once, and the engine keeps speculating afterwards."""
    periodic = lambda rid, i: (11, 12, 13)[i % 3]
    for pipeline in (False, True):
        # fault at the 3rd launch, spec on: cohort errors, engine lives
        be = FakeBackend(slots=2, max_length=16, eos=1, token_fn=periodic,
                         spec_tokens="2", fail_at_launch=3)
        eng = Engine(be, request_timeout_s=30.0, pipeline=pipeline).start()
        futs = [eng.submit([2, 3], max_new_tokens=6, rid=f"f{i}")
                for i in range(4)]
        res = [f.result(timeout=60.0) for f in futs]
        assert {r.outcome for r in res} <= {"ok", "error"}
        # post-fault requests complete and match plain greedy
        fut = eng.submit([2, 3], max_new_tokens=6, rid="after")
        after = fut.result(timeout=60.0)
        assert after.outcome == "ok"
        assert after.tokens == [11, 12, 13, 11, 12, 13]
        # cancel races the verify in flight: terminal outcome either way
        fut2 = eng.submit([2, 3], max_new_tokens=6, rid="c0")
        eng.cancel("c0")
        assert fut2.result(timeout=60.0).outcome in ("ok", "cancelled")
        assert eng.drain(timeout=60.0)


def test_acceptance_ema_fallback_is_recompile_free():
    """An adversarial stream collapses the acceptance EMA: the engine
    falls back to plain decode (no further verify launches) WITHOUT any
    backend reconfiguration — the traced-K signature never changes, so
    there is nothing to recompile."""
    be = FakeBackend(slots=2, max_length=32, eos=1,
                     token_fn=lambda rid, i: 2 + (hash((rid, i)) % 97),
                     spec_tokens="4")
    eng = Engine(be, request_timeout_s=60.0).start()
    for wave in range(3):
        futs = [eng.submit([2], max_new_tokens=10, rid=f"w{wave}-{i}")
                for i in range(4)]
        [f.result(timeout=60.0) for f in futs]
    assert eng.drain(timeout=60.0)
    assert eng._spec_ema < SPEC_EMA_OFF
    stuck = be.verify_launches
    assert stuck > 0  # it DID probe before collapsing
    # keep serving plain: verify launches stop growing
    eng2 = Engine(be, request_timeout_s=60.0)  # same backend object
    assert be.verify_launches == stuck


def test_per_request_spec_off_latch():
    """One request whose stream defeats the table stops getting drafts
    (its per-request EMA latches spec_off) while the engine keeps
    speculating for the others."""
    def token_fn(rid, i):
        if rid == "bad":
            return 2 + (hash((rid, i)) % 97)
        return (11, 12, 13)[i % 3]

    be = FakeBackend(slots=2, max_length=64, eos=1, token_fn=token_fn,
                     spec_tokens="2")
    eng = Engine(be, request_timeout_s=60.0).start()
    # warm the table with the periodic idiom
    eng.seed_draft([[11, 12, 13] * 4])
    good = [eng.submit([2], max_new_tokens=24, rid=f"g{i}") for i in range(1)]
    bad = eng.submit([3], max_new_tokens=24, rid="bad")
    [f.result(timeout=60.0) for f in good]
    bad.result(timeout=60.0)
    assert eng.drain(timeout=60.0)
    # drafts were proposed for the good stream well past the point where
    # the bad request's own EMA latched off
    slots_drafted = [set(snap) for snap in be.spec_drafts]
    assert any(len(s) == 1 for s in slots_drafted[-3:]), slots_drafted


def test_engine_seed_draft():
    be = FakeBackend(slots=2, spec_tokens="2")
    eng = Engine(be)
    assert eng.seed_draft([[11, 12, 13, 11], []]) == 1
    assert eng._draft.propose([11, 12], 1) == [13]
    # spec off: seeding is a cheap no-op
    be2 = FakeBackend(slots=2)
    assert Engine(be2).seed_draft([[1, 2, 3]]) == 0


# ------------------------------------------------- telemetry and compare


def test_note_spec_counters_and_window_record():
    log = slog.RequestLog(rung=0, offered_rps=4.0, engine="continuous",
                          pipeline="on", spec="2,4", slot_dtype="bf16")
    log.note_spec(8, 6)
    log.note_spec(4, 1)
    rec = log.window_record(window_s=1.0)
    assert rec["spec"] == "2,4" and rec["slot_dtype"] == "bf16"
    assert rec["spec_proposed"] == 12 and rec["spec_accepted"] == 7
    assert rec["accept_rate"] == round(7 / 12, 4)
    assert obs.registry().counter("serve.spec_proposed").value == 12
    assert obs.registry().counter("serve.spec_accepted").value == 7
    # no speculation: the fields stay off the record entirely
    rec2 = slog.RequestLog(rung=0, engine="continuous").window_record(1.0)
    for k in ("spec", "slot_dtype", "spec_proposed", "accept_rate"):
        assert k not in rec2


def test_serve_report_accept_column_and_summary():
    doc = {
        "rungs": [
            {"rung": 0, "offered_rps": 2.0, "arrived": 8, "completed": 8,
             "engine": "continuous", "goodput_tok_s": 40.0, "bound": "?",
             "spec": "4", "spec_proposed": 10, "spec_accepted": 8,
             "accept_rate": 0.8, "slot_dtype": "bf16"},
        ],
        "knee_rps": None, "engines": ["continuous"], "pipelines": [],
        "groups": ["serve_decode", "serve_verify"], "requests": 8,
        "compiles": 3, "recompiles": 0, "roofline": None,
        "run_ended": True, "invalid_records": 0,
    }
    text = slog.format_report(doc)
    assert "accept" in text
    assert "80.0%" in text
    assert "speculative decode: ladder 4" in text
    assert "8/10 draft tokens accepted" in text
    assert "slot state dtype: bf16" in text
    # serve_verify is a first-class serve group
    assert "serve_verify" in text


def test_serve_groups_include_verify():
    assert "serve_verify" in slog.SERVE_GROUPS


def _bench_line(rungs, **extra):
    return json.dumps(dict(
        {"metric": "serve_goodput", "value": max(
            (r.get("goodput_tok_s", 0.0) for r in rungs), default=0.0),
         "rungs": rungs}, **extra))


def test_compare_learns_accept_rate_and_slot_bytes(tmp_path):
    from paddle_tpu.observability.compare import compare, load_side

    def rung(rate, goodput, **kw):
        return dict({"offered_rps": rate, "goodput_tok_s": goodput,
                     "engine": "continuous", "pipeline": "on"}, **kw)

    # A: pre-PR-20 artifact (no spec fields at all); B: spec-on with
    # acceptance + a slot_bytes stamp
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(_bench_line([rung(2.0, 100.0), rung(8.0, 200.0)]))
    b.write_text(_bench_line(
        [rung(2.0, 110.0, spec="4", accept_rate=0.75, slot_bytes=400),
         rung(8.0, 300.0, spec="4", accept_rate=0.8, slot_bytes=400)],
        slot_bytes=400))
    sa, sb = load_side(str(a)), load_side(str(b))
    # zero-filled on the old side: the keys join and 0 -> N is judged
    assert sa["serve.2rps.accept_rate"] == 0.0
    assert sb["serve.2rps.accept_rate"] == 0.75
    # slot_bytes conditional-only: no phantom key minted on the old side
    assert "serve.2rps.slot_bytes" not in sa
    assert sb["serve.2rps.slot_bytes"] == 400.0
    assert sb["slot_bytes"] == 400.0
    doc = compare(sa, sb)
    row = {r["metric"]: r for r in doc["metrics"]}
    assert row["serve.2rps.accept_rate"]["higher_is_better"] is True
    assert row["serve.2rps.accept_rate"]["verdict"] == "IMPROVED"
    assert row["serve.8rps.goodput_tok_s"]["verdict"] == "IMPROVED"
    assert doc["verdict"] == "IMPROVED"


def test_compare_slot_bytes_lower_is_better(tmp_path):
    from paddle_tpu.observability.compare import compare, load_side

    a = tmp_path / "f32.json"
    b = tmp_path / "bf16.json"
    a.write_text(_bench_line([], slot_bytes=800))
    b.write_text(_bench_line([], slot_bytes=410))
    doc = compare(load_side(str(a)), load_side(str(b)))
    row = {r["metric"]: r for r in doc["metrics"]}
    assert row["slot_bytes"]["higher_is_better"] is False
    assert row["slot_bytes"]["verdict"] == "IMPROVED"


def test_compare_key_qualifies_spec_collision(tmp_path):
    """A both-configs sweep in ONE artifact (spec-on + spec-off rungs at
    the same rates) must not diff a config against itself: the second
    config's rungs pick up the spec qualifier."""
    from paddle_tpu.observability.compare import load_side

    rungs = [
        {"offered_rps": 2.0, "goodput_tok_s": 100.0 + 10 * i,
         "engine": "continuous", "pipeline": "on", "spec": spec}
        for i, spec in enumerate(["off", "2", "4", "8"])
    ]
    p = tmp_path / "both.json"
    p.write_text(_bench_line(rungs))
    side = load_side(str(p))
    # the collision chain walks engine -> pipeline -> spec: the fourth
    # same-rate rung lands on a spec-qualified key, none is dropped
    specced = [k for k in side if ".spec-" in k]
    assert specced, sorted(side)
    assert len({k for k in side if k.endswith("goodput_tok_s")}) == 4


# --------------------------------------------------- device-modeled A/B


class DeviceModeledSpecBackend(FakeBackend):
    """FakeBackend + the device cost model (PR-13 precedent: CPU wall
    clock can't exhibit device concurrency/batching, so the launch costs
    are modeled). A plain micro-step pays the full sequential cost (the
    vocab projection cannot batch: token t+1's input is step t's
    argmax); a verify position pays only the recurrence — with drafts
    the inputs are known up front, so the vocab scoring of all K
    positions batches into one matmul (amortized into the launch
    floor). That asymmetry IS speculative decoding's win on a real
    accelerator."""

    LAUNCH_S = 0.002   # dispatch + readback floor, either launch kind
    STEP_S = 0.002     # plain micro-step: sequential score+select
    REC_S = 0.0005     # verify position: recurrence only, scoring batched

    def dispatch(self, block=None, draft=None):
        if draft:
            u = max((len(t) for t in draft.values()), default=1)
            cc.sleep(self.LAUNCH_S + self.REC_S * max(u, 1))
        else:
            u = max(int(block), 1) if block else self.chunk
            cc.sleep(self.LAUNCH_S + self.STEP_S * u)
        super().dispatch(block=block, draft=draft)


def _modeled_rung(spec, rate, n=24):
    periodic = lambda rid, i: (11, 12, 13)[i % 3]
    be = DeviceModeledSpecBackend(slots=4, max_length=64, eos=1,
                                  token_fn=periodic, chunk="1,2,4",
                                  spec_tokens=spec)
    eng = Engine(be, request_timeout_s=120.0).start()
    if spec:
        eng.seed_draft([[11, 12, 13] * 6])
    reqs = slog.schedule_requests(
        rate, n, seed=5, prompt_fn=lambda r, i: [2, 3],
        budget_fn=lambda r, i: 16)
    w = drive_rung(eng, reqs, rate_rps=rate, rung=0)
    assert eng.drain(timeout=120.0)
    return w


def test_device_modeled_spec_beats_plain_at_overload():
    """The measured A/B under the device cost model: at an overload
    rung (offered far above capacity) spec-on's goodput beats spec-off,
    the window records a high accept_rate, and `paddle compare` renders
    the verdict IMPROVED on the goodput key."""
    from paddle_tpu.observability.compare import compare

    rate = 500.0  # far above modeled capacity: both sides saturate
    w_off = _modeled_rung(None, rate)
    w_on = _modeled_rung("4", rate)
    assert w_on.get("accept_rate", 0.0) > 0.5, w_on
    assert w_on["goodput_tok_s"] > w_off["goodput_tok_s"] * 1.1, (
        w_on["goodput_tok_s"], w_off["goodput_tok_s"])
    doc = compare(
        {"serve.500rps.goodput_tok_s": w_off["goodput_tok_s"],
         "serve.500rps.accept_rate": 0.0},
        {"serve.500rps.goodput_tok_s": w_on["goodput_tok_s"],
         "serve.500rps.accept_rate": w_on["accept_rate"]},
    )
    assert doc["verdict"] == "IMPROVED"
    assert "serve.500rps.goodput_tok_s" in doc["improvements"]


# ------------------------------------------------------- jax backend


@pytest.fixture(scope="module")
def gen_machine():
    from paddle_tpu.flagship import nmt_gen_config
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of

    tc = nmt_gen_config(vocab=50, dim=16, beam_size=1, max_length=8,
                        dtype="float32", batch_size=2)
    gm = GradientMachine(tc.model_config,
                         compute_dtype=compute_dtype_of(tc.opt_config))
    return gm, gm.init_params(seed=1)


def _jax_tokens(gm, params, *, spec=None, slot_dtype="f32", pipeline=False,
                slots=3, registry=None, n=5, budget=6):
    from paddle_tpu.serving.jax_backend import JaxDecodeBackend

    be = JaxDecodeBackend(gm, params, slots=slots, prompt_tokens=4,
                          decode_block="1,2", spec_tokens=spec,
                          slot_dtype=slot_dtype, registry=registry)
    eng = Engine(be, request_timeout_s=120.0, pipeline=pipeline).start()
    futs = [eng.submit([5 + i, 9], max_new_tokens=budget, rid=f"r{i}")
            for i in range(n)]
    res = [f.result(timeout=120.0) for f in futs]
    assert eng.drain(timeout=60.0)
    assert all(r.outcome == "ok" for r in res), [r.outcome for r in res]
    return [r.tokens for r in res], be


def test_jax_spec_parity_and_verify_recompiles(gen_machine):
    """serve_verify on device: exact greedy parity across the K ladder
    and both loops, ONE compiled signature (the traced-k bound), zero
    recompiles after warmup."""
    import jax

    from paddle_tpu.observability.compile_log import CompileRegistry

    gm, params = gen_machine
    golden, _ = _jax_tokens(gm, params)
    for spec in ("2", "1,3"):
        for pipeline in (False, True):
            reg = CompileRegistry(device_kind=jax.devices()[0].device_kind)
            toks, _be = _jax_tokens(gm, params, spec=spec,
                                    pipeline=pipeline, registry=reg)
            assert toks == golden, (spec, pipeline)
            # ONE serve_verify compile (the warmup's) — serving added none
            assert reg._group_compiles.get("serve_verify") == 1, (
                spec, pipeline, reg._group_compiles)


def test_jax_bf16_slot_state_parity_and_capacity(gen_machine):
    """bf16 slot storage: token parity within the plan's tolerance, and
    the memory_analysis proof — bf16 at DOUBLE the slots fits in the
    f32 footprint (arg bytes), the capacity the precision bought."""
    import jax

    from paddle_tpu.observability.compile_log import CompileRegistry

    gm, params = gen_machine
    f32, be32 = _jax_tokens(gm, params, slot_dtype="f32")
    bf16, be16 = _jax_tokens(gm, params, slot_dtype="bf16")
    flat32 = [t for r in f32 for t in r]
    flat16 = [t for r in bf16 for t in r]
    mismatches = sum(1 for a, b in zip(flat32, flat16) if a != b)
    assert mismatches / max(len(flat32), 1) <= be16.parity_tol, (
        mismatches, len(flat32))
    # per-slot device state roughly halves
    assert be16.slot_state_bytes() < 0.62 * be32.slot_state_bytes(), (
        be16.slot_state_bytes(), be32.slot_state_bytes())

    def arg_bytes(slot_dtype, slots):
        reg = CompileRegistry(device_kind=jax.devices()[0].device_kind)
        _jax_tokens(gm, params, slot_dtype=slot_dtype, slots=slots,
                    registry=reg, n=2, budget=3)
        row = next(r for r in reg.static_memory_rows()
                   if r.get("group") == "serve_decode")
        return row["mem_arg_bytes"]

    f32_b = arg_bytes("f32", 4)
    bf16_2x = arg_bytes("bf16", 8)
    # args = params + slots * per-slot state: halving the state pays for
    # doubling the slots (small tolerance for non-state scalars)
    assert bf16_2x <= f32_b * 1.05, (bf16_2x, f32_b)


def test_jax_spec_with_bf16_combined(gen_machine):
    """Both tentpole halves together: speculative verify over bf16 slot
    state still matches the bf16 plain stream exactly."""
    gm, params = gen_machine
    plain, _ = _jax_tokens(gm, params, slot_dtype="bf16")
    spec, be = _jax_tokens(gm, params, spec="2", slot_dtype="bf16")
    assert spec == plain
    assert be.slot_dtype == "bf16" and be.spec_blocks == (2,)
