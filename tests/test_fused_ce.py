"""Fused log-softmax cross-entropy (logits side-table).

When a layer's activation is a plain softmax and a multi-class
cross-entropy consumes it, the cost computes from the published
pre-softmax logits (paddle_tpu/layers/cost.py `_fused_softmax_ce`)
instead of re-upcasting the materialized probabilities — the TPU
bandwidth fix for big-vocab losses (reference workload:
demo/seqToseq, /root/reference/paddle/gserver/layers/CostLayer.cpp
multi-class CE semantics). These tests pin (a) numerical equivalence
with the probability-path formulation, (b) that the fused path actually
engages for the direct-softmax and hoisted-epilogue (NMT) graphs, and
(c) that dropout/error-clipping layers keep the honest probability path.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.graph import GradientMachine  # noqa: F401  (import order: graph before layers)
from paddle_tpu.layers import cost as cost_mod


def test_fused_matches_prob_path_values_and_grads():
    rng = np.random.RandomState(0)
    z = jnp.asarray(rng.randn(16, 50).astype("float32") * 3.0)
    ids = jnp.asarray(rng.randint(0, 50, (16,)).astype("int32"))

    def fused(z):
        return jnp.sum(cost_mod._fused_softmax_ce(z, ids))

    def probs(z):
        p = jax.nn.softmax(z, axis=-1)
        picked = jnp.take_along_axis(p, ids[:, None], axis=-1)[..., 0]
        return jnp.sum(-jnp.log(picked))

    np.testing.assert_allclose(fused(z), probs(z), rtol=1e-5)
    gf, gp = jax.grad(fused)(z), jax.grad(probs)(z)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gp), atol=1e-5)


def _count_fused(monkeypatch):
    calls = []
    orig = cost_mod._fused_softmax_ce

    def spy(z, ids):
        calls.append(z.shape)
        return orig(z, ids)

    monkeypatch.setattr(cost_mod, "_fused_softmax_ce", spy)
    return calls


def _loss_of(tc, batch, seed=1):
    from paddle_tpu.graph import GradientMachine

    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=seed)
    loss, _, _, _ = gm.grad_fn()(params, batch, jax.random.PRNGKey(0))
    return float(loss)


def test_fused_path_engages_for_softmax_classifier(monkeypatch):
    from paddle_tpu.flagship import example_batch, flagship_config

    calls = _count_fused(monkeypatch)
    tc = flagship_config()
    loss = _loss_of(tc, example_batch(B=4, T=8))
    assert calls, "softmax classifier should take the fused CE path"
    assert np.isfinite(loss) and loss < 2 * np.log(2)


def test_fused_path_engages_for_hoisted_nmt(monkeypatch):
    from paddle_tpu.flagship import nmt_batch, nmt_config

    calls = _count_fused(monkeypatch)
    tc = nmt_config(vocab=120, dim=16, batch_size=4)
    loss = _loss_of(tc, nmt_batch(vocab=120, B=4, T=6))
    # the vocab projection is hoisted out of the decoder scan; the fused
    # path must survive via the re-published out-link logits
    assert any(s[-1] == 120 for s in calls), calls
    assert np.isfinite(loss)


def test_fused_loss_matches_prob_loss_when_disabled(monkeypatch):
    from paddle_tpu.flagship import nmt_batch, nmt_config

    tc = nmt_config(vocab=80, dim=16, batch_size=4)
    batch = nmt_batch(vocab=80, B=4, T=5)
    fused_loss = _loss_of(tc, batch)
    # forcing the probability path must agree in f32 — this catches any
    # misalignment (transpose/reshape) in the hoisted logits re-publish
    monkeypatch.setattr(cost_mod, "_USE_FUSED_CE", False)
    prob_loss = _loss_of(tc, batch)
    np.testing.assert_allclose(fused_loss, prob_loss, rtol=1e-5)


def test_dropout_softmax_layer_keeps_prob_path(monkeypatch):
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        ExtraAttr,
        SoftmaxActivation,
        classification_cost,
        data_layer,
        fc_layer,
        outputs,
        settings,
    )

    calls = _count_fused(monkeypatch)
    with fresh_context() as ctx:
        settings(batch_size=4, learning_rate=0.1)
        x = data_layer(name="x", size=8)
        out = fc_layer(input=x, size=4, act=SoftmaxActivation(),
                       name="out", layer_attr=ExtraAttr(drop_rate=0.5))
        label = data_layer(name="label", size=4)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()

    from paddle_tpu.graph.argument import make_dense, make_ids

    rng = np.random.RandomState(0)
    batch = {
        "x": make_dense(rng.randn(4, 8).astype("float32")),
        "label": make_ids(rng.randint(0, 4, (4,)).astype("int32")),
    }
    loss = _loss_of(tc, batch)
    assert np.isfinite(loss)
    assert not calls, "dropout-after-softmax must not take the logits path"
