"""rank-auc and per-sequence classification-error evaluators, config-wired."""

import os

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.graph.argument import Argument
from paddle_tpu.proto import EvaluatorConfig
from paddle_tpu.trainer import evaluators as ev


def test_rank_auc_exact():
    cfg = EvaluatorConfig(name="r", type="rank-auc", input_layers=["s", "c"])
    e = ev.evaluator_registry.get("rank-auc")(cfg)
    e.start()
    # pos scores {0.1, 0.8}, neg {0.9, 0.2}: only (0.8, 0.2) of the four
    # pos/neg pairs is correctly ranked → AUC = 1/4
    scores = np.asarray([[0.1], [0.9], [0.2], [0.8]], np.float32)
    clicks = np.asarray([[1.0], [0.0], [0.0], [1.0]], np.float32)
    e.eval_batch([Argument(value=scores), Argument(value=clicks)])
    assert abs(e.result()["rank_auc"] - 0.25) < 1e-6

    e.start()
    order = np.linspace(0, 1, 20)[:, None].astype(np.float32)
    lab = (order[:, 0] > 0.6).astype(np.float32)[:, None]
    e.eval_batch([Argument(value=order), Argument(value=lab)])
    assert e.result()["rank_auc"] == 1.0


def test_seq_classification_error_masks_padding():
    cfg = EvaluatorConfig(name="s", type="seq_classification_error",
                          input_layers=["o", "l"])
    e = ev.evaluator_registry.get("seq_classification_error")(cfg)
    e.start()
    v = np.zeros((2, 4, 2), np.float32)
    v[0, :, 1] = 1.0           # predicts 1 everywhere
    v[1, :, 0] = 1.0           # predicts 0 everywhere
    lens = np.asarray([2, 4], np.int32)
    labels = np.asarray([[1, 1, 0, 0],      # wrong only in padding → correct
                         [0, 0, 0, 1]],     # wrong at a valid frame → wrong
                        np.int32)
    e.eval_batch([
        Argument(value=v, seq_lengths=lens),
        Argument(ids=labels, seq_lengths=lens),
    ])
    assert e.result()["seq_classification_error"] == 0.5


def test_dsl_wrappers_emit_configs():
    from paddle_tpu.config.builder import fresh_context
    from paddle_tpu.trainer_config_helpers import (
        classification_cost,
        data_layer,
        fc_layer,
        outputs,
        rank_auc_evaluator,
        seq_classification_error_evaluator,
        settings,
        SoftmaxActivation,
    )

    with fresh_context() as ctx:
        settings(batch_size=8, learning_rate=0.1)
        d = data_layer("x", size=4)
        out = fc_layer(input=d, size=2, act=SoftmaxActivation())
        label = data_layer("label", size=2)
        rank_auc_evaluator(input=out, click=label)
        seq_classification_error_evaluator(input=out, label=label)
        outputs(classification_cost(input=out, label=label))
        tc = ctx.finalize()
    types = [e.type for e in tc.model_config.evaluators]
    assert "rank-auc" in types and "seq_classification_error" in types


def test_validation_layers_parse_train_and_report(tmp_path):
    """auc-validation / pnpair-validation compat (ref: ValidationLayer.h:
    52,84; config_parser.py:1703-1704): a reference-style config using
    both parses, trains, and reports the metrics through test()."""
    import textwrap

    train_list = tmp_path / "train.list"
    train_list.write_text("1\n")
    cfg_src = textwrap.dedent(f"""
    from paddle_tpu.trainer_config_helpers import *
    define_py_data_sources2(train_list={str(train_list)!r},
                            test_list={str(train_list)!r},
                            module="synthetic_bow", obj="process")
    settings(batch_size=32, learning_rate=0.3)
    data = data_layer(name="word", size=100)
    output = fc_layer(input=data, size=2, act=SoftmaxActivation(), name="output")
    label = data_layer(name="label", size=2)
    av = auc_validation(input=output, label=label)
    # info: one query group for every row (single-column layer -> qid 0)
    qid = fc_layer(input=data, size=1, act=LinearActivation(), name="qid")
    pv = pnpair_validation(input=output, label=label, info=qid)
    outputs(classification_cost(input=output, label=label), av, pv)
    """)
    cfg_path = tmp_path / "cfg.py"
    cfg_path.write_text(cfg_src)

    import sys as _sys

    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    providers = os.path.join(REPO, "tests", "providers")
    _sys.path.insert(0, providers)
    FLAGS.save_dir = ""
    FLAGS.log_period = 0
    try:
        cfg = parse_config(str(cfg_path))
        types = {l.type for l in cfg.model_config.layers}
        assert {"auc-validation", "pnpair-validation"} <= types, types
        trainer = Trainer(cfg)
        trainer.train(num_passes=2)
        metrics = trainer.test()
    finally:
        _sys.path.remove(providers)
    # the separable synthetic data trains to a strong ranking
    # (results keys are '<evaluator name>.<metric>')
    auc = [v for k, v in metrics.items() if k.endswith(".auc")]
    pnp = [v for k, v in metrics.items() if k.endswith(".pnpair_accuracy")]
    assert auc and auc[0] > 0.9, metrics
    assert pnp and pnp[0] > 0.9, metrics
    # validation layers contribute zero cost (the real cost dominates)
    assert np.isfinite(metrics["cost"])


def test_pnpair_vectorized_matches_reference_loop():
    """The vectorized pair walk must agree with the reference's O(n^2)
    loop semantics (PnpairEvaluator::stat: pair weight = mean of sample
    weights, ties 0.5) on randomized grouped data."""
    rng = np.random.RandomState(0)
    e = ev.evaluator_registry.get("pnpair")(EvaluatorConfig(name="p", type="pnpair"))
    n = 120
    qids = rng.randint(0, 5, n)
    labels = rng.randint(0, 3, n)
    scores = np.round(rng.rand(n), 2)  # rounding forces ties
    weights = rng.rand(n) + 0.5
    e.records = list(zip(qids.tolist(), labels.tolist(),
                         scores.tolist(), weights.tolist()))
    got = e.result()["pnpair_accuracy"]
    # sub-unit total pair weight must not deflate the metric
    e2 = ev.evaluator_registry.get("pnpair")(EvaluatorConfig(name="p2", type="pnpair"))
    e2.records = [(0, 1, 0.9, 0.5), (0, 0, 0.1, 0.5)]  # one pair, weight 0.5
    assert e2.result()["pnpair_accuracy"] == 1.0

    # reference loop
    from collections import defaultdict

    by_q = defaultdict(list)
    for q, l, s, w in e.records:
        by_q[q].append((l, s, w))
    pos, total = 0.0, 0.0
    for items in by_q.values():
        for i in range(len(items)):
            for j in range(i + 1, len(items)):
                li, si, wi = items[i]
                lj, sj, wj = items[j]
                if li == lj:
                    continue
                w = (wi + wj) / 2.0
                total += w
                hi, lo = (si, sj) if li > lj else (sj, si)
                if hi > lo:
                    pos += w
                elif hi == lo:
                    pos += 0.5 * w
    expected = pos / total
    np.testing.assert_allclose(got, expected, rtol=1e-12)
