"""Socket transport (doc/serving.md "Cross-host fleet"): length-prefixed
framing with torn-tail tolerance, the per-connection reconnect/backoff
state machine, deadline propagation over the wire, the server's
dedupe/hello/deadline-shed admission, hedged retries through the fleet
router, the transport-qualified compare join — and the cross-host chaos
e2e: a real `paddle serve --listen` pair behind `paddle serve-fleet
--replica_addr`, surviving net.drop resets and a replica kill with
every request answered exactly once, plus pipe-vs-socket golden parity
and the `paddle trace` net.* hop reconstruction."""

import importlib.util
import json
import os
import socket
import struct
import subprocess
import sys

import pytest

from paddle_tpu.observability import metrics as obs
from paddle_tpu.observability.analyze import load_run
from paddle_tpu.observability.compare import _serve_key
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving import transport
from paddle_tpu.serving.fleet import FleetRouter, merge_windows
from paddle_tpu.serving.transport import (
    EngineSocketServer,
    FrameError,
    FrameReader,
    SocketEngineClient,
    SocketReplica,
    SocketTransport,
    encode_frame,
    parse_addr,
)
from paddle_tpu.utils import concurrency as cc
from paddle_tpu.utils.flags import flag_values
from paddle_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.net

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the race spec's FakeWire/_pipe/HedgeReplica are the reference
# in-process wire + replica fakes — reuse them rather than fork copies
# that could drift (the test_serve_fleet idiom)
_spec = importlib.util.spec_from_file_location(
    "spec_transport",
    os.path.join(REPO, "tests", "race_specs", "spec_transport.py"))
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
FakeWire = _mod.FakeWire
_pipe = _mod._pipe
HedgeReplica = _mod.HedgeReplica


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    obs.registry().reset()
    yield
    obs.configure("")
    faultinject.configure("")


def _wait_for(cond, timeout=30.0, msg="condition"):
    deadline = cc.monotonic() + timeout
    while cc.monotonic() < deadline:
        if cond():
            return
        cc.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# -------------------------------------------------------------- framing


def test_frame_roundtrip_and_torn_tail():
    doc = {"id": "r1", "prompt": [1, 2, 3], "nested": {"a": 1}}
    data = encode_frame(doc)
    reader = FrameReader()
    # a torn tail: the frame arrives in three fragments, the doc decodes
    # only once the final byte lands — and exactly once
    assert reader.feed(data[:3]) == []
    assert reader.feed(data[3:-2]) == []
    assert reader.feed(data[-2:]) == [doc]
    assert reader.pending_bytes() == 0
    # two frames in one read plus a torn third
    d2, d3 = {"id": "a"}, {"id": "b"}
    blob = encode_frame(d2) + encode_frame(d3) + encode_frame(doc)[:5]
    assert reader.feed(blob) == [d2, d3]
    assert reader.pending_bytes() == 5


def test_frame_reader_skips_garbage_keeps_stream():
    reader = FrameReader()
    garbage = b"\x00\x00\x00\x04not{"  # valid length, invalid JSON
    good = encode_frame({"id": "ok"})
    out = reader.feed(garbage[:8] + good)
    # the undecodable frame is skipped, the stream stays aligned
    assert out == [{"id": "ok"}]


def test_frame_oversized_header_rejected():
    reader = FrameReader()
    huge = struct.pack("!I", transport.MAX_FRAME_BYTES + 1)
    with pytest.raises(FrameError):
        reader.feed(huge + b"x")
    with pytest.raises(FrameError):
        encode_frame({"id": "x" * (transport.MAX_FRAME_BYTES + 16)})


def test_parse_addr():
    assert parse_addr("10.0.0.2:9000") == ("10.0.0.2", 9000)
    assert parse_addr(":0") == ("0.0.0.0", 0)
    with pytest.raises(ValueError):
        parse_addr("no-port")


# ------------------------------------------------ transport state machine


def test_transport_reconnects_after_drop_and_redelivers():
    decoded, conns = [], []
    lock = cc.Lock()

    def serve(wire):
        reader = FrameReader()
        while True:
            data = wire.recv(65536)
            if not data:
                return
            for doc in reader.feed(data):
                with lock:
                    decoded.append(doc)

    def connect(addr):
        a, b = _pipe()
        with lock:
            conns.append(b)
        cc.Thread(target=serve, args=(b,), daemon=True).start()
        return a

    policy = RetryPolicy(max_attempts=100, base_delay=0.001,
                         max_delay=0.005, jitter=0.0, name="net.connect")
    t = SocketTransport("c0", "fake:0", on_frame=lambda d: None,
                        policy=policy, connect_fn=connect)
    t.start()
    _wait_for(lambda: t.state == transport.UP, msg="first connect")
    assert t.send({"id": "before"})
    with lock:
        conns[0].close()  # the drop
    _wait_for(lambda: t.reconnects >= 1, msg="reconnect")
    _wait_for(lambda: t.send({"id": "after"}), msg="send on new wire")
    _wait_for(lambda: any(d.get("id") == "after" for d in decoded),
              msg="delivery on reconnected wire")
    t.close()
    assert t.join(timeout=10.0)
    assert t.state == transport.CLOSED
    ids = [d["id"] for d in decoded]
    assert len(ids) == len(set(ids)), ids  # nothing decodes twice


def test_transport_backoff_budget_exhaustion_closes():
    attempts = []

    def connect(addr):
        attempts.append(cc.monotonic())
        raise OSError("connection refused")

    policy = RetryPolicy(max_attempts=3, base_delay=0.01, max_delay=0.02,
                         multiplier=2.0, jitter=0.0, name="net.connect")
    t = SocketTransport("c0", "fake:0", on_frame=lambda d: None,
                        policy=policy, connect_fn=connect)
    t.start()
    _wait_for(t.closed, msg="budget exhaustion")
    assert t.join(timeout=10.0)
    assert t.state == transport.CLOSED
    assert len(attempts) == 3  # the budget, exactly
    # CLOSED is terminal: sends refuse instead of buffering silently
    assert t.send({"id": "x"}) is False


# --------------------------------------------- replica + server contract


class _ManualFut:
    def __init__(self):
        self._ev = cc.Event()
        self._res = None

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        if not self._ev.wait(timeout):
            raise TimeoutError("unresolved")
        return self._res

    def resolve(self, res):
        self._res = res
        self._ev.set()


class _Res:
    def __init__(self, tokens=(1, 2), outcome="ok"):
        self.outcome = outcome
        self.tokens = list(tokens)
        self.error = ""
        self.retry_after_s = None


class _FakeEngine:
    """Engine duck-type with manually-resolved futures so tests control
    exactly when answers cross the wire."""

    def __init__(self):
        self.lock = cc.Lock()
        self.subs = {}  # rid -> (fut, timeout_s)

    def submit(self, prompt, max_new_tokens=None, rid=None, timeout_s=None,
               replay=False, trace=""):
        fut = _ManualFut()
        with self.lock:
            self.subs[rid] = (fut, timeout_s)
        return fut

    def status(self):
        return {"state": "serving", "queue_depth": 0, "occupancy": 0.0}


def test_replica_stamps_deadline_once_and_delivers():
    eng, eng2 = _FakeEngine(), _FakeEngine()
    srv = EngineSocketServer(eng, "127.0.0.1:0")
    srv2 = EngineSocketServer(eng2, "127.0.0.1:0")
    srv.start(), srv2.start()
    try:
        got = []

        def deliver(name, doc):
            got.append((name, doc))

        rep = SocketReplica("replica-0", srv.address, deliver=deliver,
                            timeout_s=30.0).start()
        doc = {"id": "d0", "prompt": [1, 2], "max_new_tokens": 2}
        _wait_for(lambda: rep.send(doc), msg="send over loopback")
        # the wall-clock deadline landed in the SHARED doc, once
        assert "deadline_unix" in doc
        stamped = doc["deadline_unix"]
        assert stamped == pytest.approx(transport.wall_time() + 30.0, abs=5.0)
        _wait_for(lambda: "d0" in eng.subs, msg="server submit")
        fut, timeout_s = eng.subs["d0"]
        # the server shrank the budget to the wire remainder
        assert timeout_s is not None and 0 < timeout_s <= 30.0
        # a re-offer to ANOTHER replica keeps the ORIGINAL deadline even
        # though replica-1's own timeout budget is far larger
        rep2 = SocketReplica("replica-1", srv2.address, deliver=deliver,
                             timeout_s=600.0).start()
        _wait_for(lambda: rep2.send(doc), msg="re-offer send")
        assert doc["deadline_unix"] == stamped
        fut.resolve(_Res(tokens=[7, 8]))
        _wait_for(lambda: len(got) >= 1, msg="answer delivery")
        name, ans = got[0]
        assert name == "replica-0" and ans["id"] == "d0"
        assert ans["outcome"] == "ok" and ans["tokens"] == [7, 8]
        rep.kill(), rep2.kill()
        assert rep.join(10.0) and rep2.join(10.0)
    finally:
        srv.close(), srv2.close()


def test_server_sheds_expired_deadline_on_arrival():
    eng = _FakeEngine()
    srv = EngineSocketServer(eng, "127.0.0.1:0")
    srv.start()
    try:
        got = []
        rep = SocketReplica("replica-0", srv.address,
                            deliver=lambda n, d: got.append(d),
                            timeout_s=30.0).start()
        doc = {"id": "late", "prompt": [1],
               "deadline_unix": transport.wall_time() - 5.0}
        _wait_for(lambda: rep.send(doc), msg="send expired doc")
        _wait_for(lambda: len(got) >= 1, msg="shed answer")
        assert got[0]["id"] == "late"
        assert got[0]["outcome"] == "timeout", got[0]
        # the engine never saw it — the remote replica shed locally
        assert "late" not in eng.subs
        rep.kill()
        assert rep.join(10.0)
    finally:
        srv.close()


def test_reconnect_hello_answer_arrives_exactly_once():
    """Kill the live connection while a request is in flight: the
    replica reconnects, the hello names it outstanding, the server
    (which still holds it in flight) answers on the NEW wire — exactly
    once, no re-submit."""
    eng = _FakeEngine()
    srv = EngineSocketServer(eng, "127.0.0.1:0")
    srv.start()
    try:
        got = []
        rep = SocketReplica("replica-0", srv.address,
                            deliver=lambda n, d: got.append(d),
                            timeout_s=60.0).start()
        _wait_for(lambda: rep.send({"id": "h0", "prompt": [1],
                                    "max_new_tokens": 1}), msg="send")
        _wait_for(lambda: "h0" in eng.subs, msg="server submit")
        with rep._lock:
            t = rep._transport
        # sever the wire server-side: the client must reconnect
        with srv._lock:
            conn = srv._conn
        transport._close_wire(conn)
        _wait_for(lambda: t.reconnects >= 1, msg="reconnect")
        eng.subs["h0"][0].resolve(_Res())
        _wait_for(lambda: len(got) >= 1, msg="answer after reconnect")
        cc.sleep(0.2)  # absorb any (wrong) duplicate delivery
        assert [d["id"] for d in got] == ["h0"]
        # in flight during the hello meant: no duplicate engine submit
        assert len(eng.subs) == 1
        rep.kill()
        assert rep.join(10.0)
    finally:
        srv.close()


def test_server_dedupes_by_id_and_resends_answered():
    eng = _FakeEngine()
    srv = EngineSocketServer(eng, "127.0.0.1:0")
    srv.start()
    try:
        cli = SocketEngineClient(srv.address)
        cli.start()
        fut = cli.submit({"id": "q0", "prompt": [1], "max_new_tokens": 1})
        _wait_for(lambda: "q0" in eng.subs, msg="submit")
        eng.subs["q0"][0].resolve(_Res(tokens=[3]))
        assert fut.result(timeout=30)["tokens"] == [3]
        # duplicate delivery (a hedge loser, a net.dup): the stored
        # answer is re-sent, the engine is NOT re-submitted
        fut2 = cli.submit({"id": "q0", "prompt": [1], "max_new_tokens": 1})
        assert fut2.result(timeout=30)["tokens"] == [3]
        assert len(eng.subs) == 1
        cli.close()
    finally:
        srv.close()


def test_replica_health_stale_without_pongs():
    # a listener that accepts nothing: the TCP connect succeeds (backlog)
    # but no pong ever comes back — health must say stale, not lie
    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    try:
        addr = f"127.0.0.1:{lst.getsockname()[1]}"
        rep = SocketReplica("replica-0", addr,
                            deliver=lambda n, d: None).start()
        h = rep.health(cc.monotonic())
        assert h.get("stale") is True
        assert "no pong" in h.get("detail", "")
        rep.kill()
        assert rep.join(10.0)
    finally:
        lst.close()


# ----------------------------------------------------- hedging (router)


def test_router_hedges_slow_replica_first_answer_wins():
    emitted = []
    reps = [HedgeReplica("replica-0", delay_s=0.5),
            HedgeReplica("replica-1", delay_s=0.01)]
    router = FleetRouter(reps, emit=emitted.append, poll_s=0.005,
                         health_period_s=0.0, restart_base_delay=0.01,
                         hedge_after=0.03)
    for r in reps:
        r.deliver = router.deliver
    router.start()
    ids = [f"g{i}" for i in range(4)]
    for rid in ids:
        assert router.submit({"id": rid, "prompt": [2],
                              "max_new_tokens": 1})
    box = {}
    t = cc.Thread(target=lambda: box.setdefault("rc", router.run()),
                  daemon=True)
    t.start()
    router.note_eof()
    t.join(timeout=60.0)
    assert not t.is_alive()
    assert box["rc"] == 0
    router.shutdown(timeout=10.0)
    assert [d["id"] for d in emitted] == ids  # exactly once, in order
    st = router.status()
    # requests stuck on the slow owner were hedged to the fast replica,
    # and the fast answer won at least once
    assert st["hedges"] >= 1, st
    assert st["hedge_wins"] >= 1, st
    assert st["hedge_wins"] <= st["hedges"], st
    # the loser's late answer was absorbed, never emitted
    assert st["duplicate_answers"] <= st["hedges"], st


def test_hedge_disabled_by_default():
    emitted = []
    reps = [HedgeReplica("replica-0", delay_s=0.2),
            HedgeReplica("replica-1", delay_s=0.01)]
    router = FleetRouter(reps, emit=emitted.append, poll_s=0.005,
                         health_period_s=0.0, restart_base_delay=0.01)
    for r in reps:
        r.deliver = router.deliver
    router.start()
    assert router.submit({"id": "n0", "prompt": [2], "max_new_tokens": 1})
    box = {}
    t = cc.Thread(target=lambda: box.setdefault("rc", router.run()),
                  daemon=True)
    t.start()
    router.note_eof()
    t.join(timeout=60.0)
    assert not t.is_alive() and box["rc"] == 0
    router.shutdown(timeout=10.0)
    assert router.status()["hedges"] == 0


# -------------------------------------------- compare join + flag helper


def test_merge_windows_stamps_transport():
    w = {"engine": "continuous", "completed": 1, "gen_tokens": 2,
         "arrived": 1}
    rec = merge_windows([w], rate_rps=1.0, rung=0, window_s=1.0,
                        router_s=0.1, transport="tcp")
    assert rec["transport"] == "tcp"
    rec2 = merge_windows([w], rate_rps=1.0, rung=0, window_s=1.0)
    assert "transport" not in rec2


def test_serve_key_transport_qualifies_on_collision():
    seen = set()
    base = _serve_key(4.0, 0, seen, engine="continuous", pipeline="on",
                      replicas=2, transport="pipe")
    eng = _serve_key(4.0, 1, seen, engine="continuous", pipeline="on",
                     replicas=2, transport="pipe")
    pipe_q = _serve_key(4.0, 2, seen, engine="continuous", pipeline="on",
                        replicas=2, transport="pipe")
    tcp = _serve_key(4.0, 3, seen, engine="continuous", pipeline="on",
                     replicas=2, transport="tcp")
    assert base == "serve.x2.4rps."
    assert eng == "serve.continuous.x2.4rps."
    assert pipe_q == "serve.continuous.pipe-on.x2.4rps."
    # the 4th same-(engine, pipeline, rate) rung: transport breaks the tie
    assert tcp == "serve.continuous.pipe-on.net-tcp.x2.4rps."
    # a one-transport-per-artifact A/B joins UNQUALIFIED on offered load
    assert _serve_key(4.0, 0, set(), engine="continuous", pipeline="on",
                      replicas=2, transport="tcp") == base


def test_flag_values_collects_repeats_and_commas():
    argv = ["--replica_addr=a:1", "--x=1", "--replica_addr=b:2,c:3",
            "--replica_addr=d:4"]
    assert flag_values(argv, "replica_addr") == ["a:1", "b:2", "c:3", "d:4"]
    assert flag_values(argv, "missing") == []


# ------------------------------------------------------------ chaos e2e


SERVE_CONFIG = """
import sys
sys.path.insert(0, {demo!r})
from paddle.trainer_config_helpers import *
from seqToseq_net import gru_encoder_decoder

settings(batch_size=2, learning_rate=1e-3, learning_method=AdamOptimizer())
gru_encoder_decoder(source_dict_dim=50, target_dict_dim=50,
                    is_generating=True, word_vector_dim=16,
                    encoder_size=16, decoder_size=16, beam_size=1,
                    max_length=6)
"""

SUBPROC_ENV = dict(
    os.environ, JAX_PLATFORMS="cpu",
    PYTHONPATH=f"{REPO}:{os.path.join(REPO, 'compat')}",
)


def _write_config(tmp_path):
    cfg = tmp_path / "serve_conf.py"
    cfg.write_text(SERVE_CONFIG.format(
        demo=os.path.join(REPO, "demo", "seqToseq")))
    return cfg


def _drain(pipe, sink):
    def run():
        for line in pipe:
            sink.append(line)
    t = cc.Thread(target=run, daemon=True)
    t.start()
    return t


def _start_listen_server(tmp_path, cfg, idx, metrics_path=None, env=None):
    """One `paddle serve --listen 127.0.0.1:0` subprocess; returns
    (proc, addr, stderr_sink) once the bound-address banner prints."""
    argv = [sys.executable, "-m", "paddle_tpu.cli", "serve",
            f"--config={cfg}", "--use_tpu=0", "--listen=127.0.0.1:0",
            "--serve_slots=2", "--serve_prompt_tokens=4",
            "--serve_decode_block=1",
            f"--compile_cache_dir={tmp_path / 'ccache'}"]
    if metrics_path:
        argv.append(f"--metrics_path={metrics_path}")
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            env=env or SUBPROC_ENV, cwd=str(tmp_path))
    errs = []
    addr = None
    deadline = cc.monotonic() + 300.0
    marker = "# paddle serve: listening on "
    while cc.monotonic() < deadline:
        line = proc.stderr.readline()
        if not line:
            break
        errs.append(line)
        if line.startswith(marker):
            addr = line[len(marker):].strip()
            break
    assert addr, f"server {idx} never printed its address: {''.join(errs)}"
    # keep both pipes drained so the child never blocks on a full pipe
    _drain(proc.stderr, errs)
    _drain(proc.stdout, errs)
    return proc, addr, errs


def _fleet_requests(n):
    """The seeded schedule_requests workload both transports replay."""
    import numpy as np

    from paddle_tpu.observability import serving

    prng_holder = {}

    def prompt_fn(rng, i):
        return rng.randint(2, 49, size=int(rng.randint(1, 5))).tolist()

    reqs = serving.schedule_requests(50.0, n, 7, rung=0,
                                     prompt_fn=prompt_fn)
    del np, prng_holder
    return [{"id": r.rid, "prompt": list(r.prompt or [2, 3]),
             "max_new_tokens": int(getattr(r, "max_new", None) or 2)}
            for r in reqs]


def _answers(stdout_text):
    out = []
    for line in stdout_text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            doc = json.loads(line)
            if "outcome" in doc:
                out.append(doc)
    return out


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_socket_fleet_drop_and_replica_death(tmp_path):
    """THE acceptance scenario: two `paddle serve --listen` replicas
    behind `paddle serve-fleet --replica_addr`; the router takes an
    injected net.drop (connection reset mid-stream) AND one server
    process is killed mid-load. The transport reconnects with backoff,
    the hello handshake re-offers undelivered work, the death path
    re-offers the killed replica's outstanding to the survivor — and
    every request id is answered EXACTLY once, in order, rc 0."""
    cfg = _write_config(tmp_path)
    run_dir = tmp_path / "run"
    docs = _fleet_requests(8)
    ids = [d["id"] for d in docs]
    p0, addr0, errs0 = _start_listen_server(tmp_path, cfg, 0)
    p1, addr1, errs1 = _start_listen_server(tmp_path, cfg, 1)
    try:
        router = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
             f"--replica_addr={addr0}", f"--replica_addr={addr1}",
             "--restart_base_delay=0.01", "--restart_budget=1",
             "--io_retry_attempts=2", "--io_retry_base_delay=0.05",
             "--fault_spec=net.drop=raise@3",
             f"--fleet_status_dir={tmp_path / 'fs'}",
             f"--metrics_path={run_dir}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=SUBPROC_ENV,
            cwd=str(tmp_path))
        rerrs = []
        _drain(router.stderr, rerrs)
        for d in docs:
            router.stdin.write(json.dumps(d) + "\n")
        router.stdin.close()  # EOF batch: everything must be answered
        answers = []
        killed = False
        deadline = cc.monotonic() + 540.0
        while len(answers) < len(ids) and cc.monotonic() < deadline:
            line = router.stdout.readline()
            if not line:
                break
            line = line.strip()
            if line.startswith("{") and "outcome" in line:
                answers.append(json.loads(line))
            if len(answers) >= 2 and not killed:
                p1.kill()  # one replica dies mid-load
                killed = True
        rc = router.wait(timeout=60)
        assert killed, "load finished before the kill — raise n_requests"
        assert rc == 0, (rc, "".join(rerrs)[-4000:])
        got = [d["id"] for d in answers]
        assert got == ids, (got, "".join(rerrs)[-3000:])
        assert all(d["outcome"] == "ok" for d in answers), answers
        # the drills actually fired: the run_end counter snapshot shows
        # at least one re-established connection and the death
        recs = [r for rs in load_run(str(run_dir)).values() for r in rs]
        end = [r for r in recs if r.get("kind") == "run_end"]
        assert end, recs[-3:]
        counters = end[0].get("counters") or {}
        assert counters.get("net.reconnects", 0) >= 1, counters
        assert counters.get("fleet.deaths", 0) >= 1, counters
        assert counters.get("fleet.routed", 0) >= len(ids), counters
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)


@pytest.mark.chaos
@pytest.mark.slow
def test_golden_parity_pipe_fleet_vs_socket_fleet(tmp_path):
    """The same seeded schedule_requests workload through a pipe fleet
    and a socket fleet must produce IDENTICAL answers per id — the
    transport moves bytes, it must never move numerics."""
    cfg = _write_config(tmp_path)
    docs = _fleet_requests(6)
    ids = [d["id"] for d in docs]
    reqs = "\n".join(json.dumps(d) for d in docs) + "\n"

    pipe_out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
         f"--config={cfg}", "--use_tpu=0", "--fleet_replicas=2",
         f"--fleet_status_dir={tmp_path / 'fs_pipe'}",
         "--serve_slots=2", "--serve_prompt_tokens=4",
         "--serve_decode_block=1", "--restart_base_delay=0.01",
         f"--compile_cache_dir={tmp_path / 'ccache'}"],
        input=reqs, capture_output=True, text=True, timeout=600,
        env=SUBPROC_ENV, cwd=str(tmp_path))
    assert pipe_out.returncode == 0, pipe_out.stderr[-4000:]
    pipe_answers = {d["id"]: d for d in _answers(pipe_out.stdout)}
    assert sorted(pipe_answers) == sorted(ids)

    p0, addr0, _ = _start_listen_server(tmp_path, cfg, 0)
    p1, addr1, _ = _start_listen_server(tmp_path, cfg, 1)
    try:
        sock_out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
             f"--replica_addr={addr0}", f"--replica_addr={addr1}",
             f"--fleet_status_dir={tmp_path / 'fs_sock'}"],
            input=reqs, capture_output=True, text=True, timeout=600,
            env=SUBPROC_ENV, cwd=str(tmp_path))
        assert sock_out.returncode == 0, sock_out.stderr[-4000:]
        sock_answers = {d["id"]: d for d in _answers(sock_out.stdout)}
        assert sorted(sock_answers) == sorted(ids)
        for rid in ids:
            a, b = pipe_answers[rid], sock_answers[rid]
            assert a["outcome"] == b["outcome"] == "ok", (rid, a, b)
            assert a["tokens"] == b["tokens"], (rid, a, b)
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)


@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.trace
def test_socket_fleet_trace_net_hops_and_hedge_win(tmp_path):
    """`paddle trace` over a socket-fleet run: net.connect hops land in
    the router stream, answered requests carry net.rpc hops in their
    timelines, an injected net.stall (wedged read — pongs stop, answers
    stop) forces a hedge whose win shows up in the counters and whose
    hedge bucket is attributed in the tail table."""
    from paddle_tpu.observability.tracing import analyze_trace

    cfg = _write_config(tmp_path)
    run_dir = tmp_path / "run"
    docs = _fleet_requests(8)
    ids = [d["id"] for d in docs]
    reqs = "\n".join(json.dumps(d) for d in docs) + "\n"
    # replica streams INSIDE the run dir, where fleet_stream_dirs
    # discovers them next to the router's own stream
    p0, addr0, _ = _start_listen_server(
        tmp_path, cfg, 0,
        metrics_path=run_dir / "fleet_status" / "replica-0")
    p1, addr1, _ = _start_listen_server(
        tmp_path, cfg, 1,
        metrics_path=run_dir / "fleet_status" / "replica-1")
    try:
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.cli", "serve-fleet",
             f"--replica_addr={addr0}", f"--replica_addr={addr1}",
             "--hedge_after=0.5",
             # wedge one replica connection's read loop mid-run: its
             # pongs and answers stop, outstanding work there hedges
             "--fault_spec=net.stall=sleep:8@5",
             f"--fleet_status_dir={tmp_path / 'fs'}",
             f"--metrics_path={run_dir}"],
            input=reqs, capture_output=True, text=True, timeout=600,
            env=SUBPROC_ENV, cwd=str(tmp_path))
        assert out.returncode == 0, out.stderr[-4000:]
        answers = _answers(out.stdout)
        got = [d["id"] for d in answers]
        assert got == ids, (got, out.stderr[-3000:])
        recs = [r for rs in load_run(str(run_dir)).values() for r in rs]
        end = [r for r in recs if r.get("kind") == "run_end"]
        assert end, recs[-3:]
        counters = end[0].get("counters") or {}
        assert counters.get("net.hedges", 0) >= 1, counters
        assert counters.get("net.hedge_wins", 0) >= 1, counters
        # the net.* hops are real span records in the router stream
        span_names = {r.get("name") for r in recs if r.get("kind") == "span"}
        assert "net.connect" in span_names, span_names
        assert "net.rpc" in span_names, span_names
        assert "net.hedge" in span_names, span_names

        doc = analyze_trace([str(run_dir)])
        # router stream plus both replica streams were discovered
        assert len(doc["streams"]) >= 3, doc["streams"]
        recon = {t["rid"]: t for t in doc["requests"].values()
                 if t["answered"]}
        assert sorted(recon) == sorted(ids), sorted(recon)
        # answered requests carry the net.rpc hop in their timelines
        rpc_tls = [t for t in recon.values()
                   if "net.rpc" in [sp["name"] for sp in t["spans"]]]
        assert rpc_tls, "no timeline carries a net.rpc hop"
        # the hedged request's timeline shows the hedge hop, and the
        # hedge bucket is a named share of the attribution table
        hedged = [t for t in recon.values()
                  if "net.hedge" in [sp["name"] for sp in t["spans"]]]
        assert hedged, "no timeline carries a net.hedge hop"
        assert all(t["buckets"].get("hedge", 0.0) > 0.0 for t in hedged)
        assert doc["rungs"], doc
        assert all("hedge" in r["shares"] for r in doc["rungs"])
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
