"""Nested recurrent groups, sequence-valued memories, generation in-links.

Mirrors the reference's hierarchical-RNN equivalence tests
(/root/reference/paddle/gserver/tests/test_RecurrentGradientMachine.cpp,
sequence_nest_rnn.conf vs sequence_rnn.conf): an outer group stepping over
subsequences with an inner RNN group must match the flat RNN run over each
subsequence as an independent sequence; sequence memories
(createMemoryFrameInfo seqFlag, RecurrentGradientMachine.cpp:622) carry a
whole sequence between outer steps; generation with real sequence
in-links consumes one input frame per step.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config import parse_config
from paddle_tpu.graph import GradientMachine, make_seq
from paddle_tpu.graph.argument import Argument


def parse_str(src: str):
    import os
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(textwrap.dedent(src))
        path = f.name
    try:
        return parse_config(path)
    finally:
        os.unlink(path)


D, H = 5, 6

FLAT_RNN = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
def rnn_step(y):
    mem = memory(name="rnn_out", size=6)
    return mixed_layer(name="rnn_out", size=6, act=TanhActivation(), bias_attr=False,
        input=[full_matrix_projection(y, param_attr=ParamAttr(name="w_x")),
               full_matrix_projection(mem, param_attr=ParamAttr(name="w_h"))])
out = recurrent_group(step=rnn_step, input=x, name="flat_rnn")
outputs(out)
"""

NEST_RNN = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
def rnn_step(y):
    mem = memory(name="rnn_out", size=6)
    return mixed_layer(name="rnn_out", size=6, act=TanhActivation(), bias_attr=False,
        input=[full_matrix_projection(y, param_attr=ParamAttr(name="w_x")),
               full_matrix_projection(mem, param_attr=ParamAttr(name="w_h"))])
def outer_step(sub):
    return recurrent_group(step=rnn_step, input=sub, name="inner_rnn")
out = recurrent_group(step=outer_step, input=SubsequenceInput(x), name="outer")
outputs(out)
"""


def test_nested_rnn_matches_flat():
    """Outer-group-over-subsequences + inner RNN == flat RNN on each
    subsequence as its own sequence (the reference equivalence test)."""
    B, S, T = 2, 3, 4
    rng = np.random.RandomState(0)
    x_nest = rng.randn(B, S, T, D).astype(np.float32)
    n_subs = np.array([3, 2], np.int32)            # sample 1 has 2 subseqs
    sub_lens = np.array([[4, 2, 3], [1, 4, 0]], np.int32)

    tc_n = parse_str(NEST_RNN)
    gm_n = GradientMachine(tc_n.model_config)
    params = gm_n.init_params(seed=3)
    batch_n = {
        "x": Argument(
            value=jnp.asarray(x_nest),
            seq_lengths=jnp.asarray(n_subs),
            sub_seq_lengths=jnp.asarray(sub_lens),
        )
    }
    out_n, _ = gm_n.forward(params, batch_n, "test")
    nested = np.asarray(out_n["outer"].value)      # [B, S, T, H]
    assert out_n["outer"].sub_seq_lengths is not None

    # flat run: every VALID subsequence as an independent sequence
    pairs = [(b, s) for b in range(B) for s in range(n_subs[b])]
    x_flat = np.stack([x_nest[b, s] for b, s in pairs])          # [N, T, D]
    l_flat = np.array([sub_lens[b, s] for b, s in pairs], np.int32)
    tc_f = parse_str(FLAT_RNN)
    gm_f = GradientMachine(tc_f.model_config)
    params_f = gm_f.init_params(seed=4)
    for k in ("w_x", "w_h"):
        params_f[k] = params[k]
    out_f, _ = gm_f.forward(params_f, {"x": make_seq(jnp.asarray(x_flat), jnp.asarray(l_flat))}, "test")
    flat = np.asarray(out_f["flat_rnn"].value)     # [N, T, H]

    for i, (b, s) in enumerate(pairs):
        l = int(sub_lens[b, s])
        np.testing.assert_allclose(
            nested[b, s, :l], flat[i, :l], rtol=2e-5, atol=1e-6,
            err_msg=f"subseq {(b, s)}",
        )
    # invalid outer steps are masked to zero
    np.testing.assert_array_equal(nested[1, 2], 0.0)


FLAT_RNN_STATIC = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
z = data_layer(name="z", size=4)
enc = fc_layer(input=z, size=6, act=TanhActivation(), name="enc",
               param_attr=ParamAttr(name="w_z"), bias_attr=False)
def rnn_step(y, c):
    mem = memory(name="rnn_out", size=6)
    return mixed_layer(name="rnn_out", size=6, act=TanhActivation(), bias_attr=False,
        input=[full_matrix_projection(y, param_attr=ParamAttr(name="w_x")),
               full_matrix_projection(mem, param_attr=ParamAttr(name="w_h")),
               full_matrix_projection(c, param_attr=ParamAttr(name="w_c"))])
out = recurrent_group(step=rnn_step, input=[x, StaticInput(enc)], name="flat_rnn")
outputs(out)
"""

NEST_RNN_STATIC = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
z = data_layer(name="z", size=4)
enc = fc_layer(input=z, size=6, act=TanhActivation(), name="enc",
               param_attr=ParamAttr(name="w_z"), bias_attr=False)
def rnn_step(y, c):
    mem = memory(name="rnn_out", size=6)
    return mixed_layer(name="rnn_out", size=6, act=TanhActivation(), bias_attr=False,
        input=[full_matrix_projection(y, param_attr=ParamAttr(name="w_x")),
               full_matrix_projection(mem, param_attr=ParamAttr(name="w_h")),
               full_matrix_projection(c, param_attr=ParamAttr(name="w_c"))])
def outer_step(sub):
    return recurrent_group(step=rnn_step, input=[sub, StaticInput(enc)],
                           name="inner_rnn")
out = recurrent_group(step=outer_step, input=SubsequenceInput(x), name="outer")
outputs(out)
"""


def test_inner_group_reads_outer_scope_static():
    """An inner group's StaticInput can reference a layer OUTSIDE the outer
    group (an encoder in root scope) — the hierarchical-decoder shape."""
    B, S, T = 2, 2, 3
    rng = np.random.RandomState(4)
    x_nest = rng.randn(B, S, T, D).astype(np.float32)
    z = rng.randn(B, 4).astype(np.float32)
    sub_lens = np.array([[3, 2], [1, 3]], np.int32)
    n_subs = np.full((B,), S, np.int32)

    tc_n = parse_str(NEST_RNN_STATIC)
    gm_n = GradientMachine(tc_n.model_config)
    params = gm_n.init_params(seed=11)
    out_n, _ = gm_n.forward(
        params,
        {
            "x": Argument(
                value=jnp.asarray(x_nest),
                seq_lengths=jnp.asarray(n_subs),
                sub_seq_lengths=jnp.asarray(sub_lens),
            ),
            "z": Argument(value=jnp.asarray(z)),
        },
        "test",
    )
    nested = np.asarray(out_n["outer"].value)

    pairs = [(b, s) for b in range(B) for s in range(S)]
    x_flat = np.stack([x_nest[b, s] for b, s in pairs])
    z_flat = np.stack([z[b] for b, _ in pairs])
    l_flat = np.array([sub_lens[b, s] for b, s in pairs], np.int32)
    tc_f = parse_str(FLAT_RNN_STATIC)
    gm_f = GradientMachine(tc_f.model_config)
    params_f = gm_f.init_params(seed=12)
    for k in ("w_x", "w_h", "w_c", "w_z"):
        params_f[k] = params[k]
    out_f, _ = gm_f.forward(
        params_f,
        {
            "x": make_seq(jnp.asarray(x_flat), jnp.asarray(l_flat)),
            "z": Argument(value=jnp.asarray(z_flat)),
        },
        "test",
    )
    flat = np.asarray(out_f["flat_rnn"].value)
    for i, (b, s) in enumerate(pairs):
        l = int(sub_lens[b, s])
        np.testing.assert_allclose(
            nested[b, s, :l], flat[i, :l], rtol=2e-5, atol=1e-6,
            err_msg=f"subseq {(b, s)}",
        )


SEQ_MEM = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
x = data_layer(name="x", size=5)
boot = data_layer(name="boot", size=5)
def outer_step(sub):
    mem = memory(name="acc", size=5, is_seq=True, boot_layer=boot)
    return addto_layer(input=[sub, mem], name="acc", act=LinearActivation(),
                       bias_attr=False)
out = recurrent_group(step=outer_step, input=SubsequenceInput(x), name="nacc")
outputs(out)
"""


def test_sequence_memory_carries_whole_sequence():
    """A memory(is_seq=True) hands step s the FULL output sequence of step
    s-1: with out = sub + mem the result is a cumulative sum over
    subsequences."""
    B, S, T = 2, 3, 4
    rng = np.random.RandomState(1)
    x = rng.randn(B, S, T, D).astype(np.float32)
    n_subs = np.array([3, 2], np.int32)
    sub_lens = np.full((B, S), T, np.int32)
    sub_lens[1, 2] = 0
    tc = parse_str(SEQ_MEM)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=5)
    batch = {
        "x": Argument(
            value=jnp.asarray(x),
            seq_lengths=jnp.asarray(n_subs),
            sub_seq_lengths=jnp.asarray(sub_lens),
        ),
        "boot": make_seq(jnp.zeros((B, T, D), jnp.float32),
                         jnp.full((B,), T, jnp.int32)),
    }
    out, _ = gm.forward(params, batch, "test")
    got = np.asarray(out["nacc"].value)            # [B, S, T, D]
    want = np.cumsum(x, axis=1)
    for b in range(B):
        for s in range(n_subs[b]):
            np.testing.assert_allclose(got[b, s], want[b, s], rtol=1e-5,
                                       err_msg=f"step {(b, s)}")


GEN_INLINK = """
from paddle_tpu.trainer_config_helpers import *
settings(batch_size=4, learning_rate=1e-3)
src = data_layer(name="src", size=11)
def gen_step(x_t, prev):
    e = embedding_layer(input=x_t, size=7, name="src_emb",
                        param_attr=ParamAttr(name="Tsrc"))
    h = concat_layer(input=[e, prev], name="h")
    return fc_layer(input=h, size=9, act=SoftmaxActivation(), name="scorer")
out = beam_search(step=gen_step,
                  input=[src, GeneratedInput(size=9, embedding_name="Tgen",
                                             embedding_size=7)],
                  bos_id=0, eos_id=8, beam_size=1, max_length=8, name="gen")
"""


def test_generation_consumes_input_frames():
    """Generation with a real sequence in-link: one token per input frame
    (greedy rollout reproduced in numpy)."""
    V_in, V, E = 11, 9, 7
    B, T = 3, 5
    bos, eos = 0, 8
    rng = np.random.RandomState(2)
    src = rng.randint(0, V_in, (B, T)).astype(np.int32)
    lens = np.array([5, 3, 4], np.int32)
    tc = parse_str(GEN_INLINK)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=7)
    batch = {"src": make_seq(None, jnp.asarray(lens), ids=jnp.asarray(src))}
    out, _ = gm.forward(params, batch, "gen")
    got_ids = np.asarray(out["gen"].ids)
    got_lens = np.asarray(out["gen"].seq_lengths)

    Tsrc = np.asarray(params["Tsrc"])
    Tgen = np.asarray(params["Tgen"])
    W = np.asarray(params["_scorer.w0"])
    b_w = np.asarray(params["_scorer.wbias"]).reshape(-1)
    for i in range(B):
        prev = bos
        toks = []
        for t in range(int(lens[i])):
            h = np.concatenate([Tsrc[src[i, t]], Tgen[prev]])
            logits = h @ W + b_w
            tok = int(np.argmax(logits))
            toks.append(tok)
            if tok == eos:
                break
            prev = tok
        assert got_lens[i] == len(toks), (i, got_lens[i], toks)
        np.testing.assert_array_equal(got_ids[i, : len(toks)], toks)


def test_nested_group_gradients_flow():
    """Training through a nested group: grads exist and are finite for the
    shared RNN weights."""
    B, S, T = 2, 2, 3
    rng = np.random.RandomState(3)
    x = rng.randn(B, S, T, D).astype(np.float32)
    tc = parse_str(NEST_RNN)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=9)
    batch = {
        "x": Argument(
            value=jnp.asarray(x),
            seq_lengths=jnp.full((B,), S, jnp.int32),
            sub_seq_lengths=jnp.full((B, S), T, jnp.int32),
        )
    }

    def loss(p):
        outs, _ = gm.forward(p, batch, "train", rng=jax.random.PRNGKey(0))
        return jnp.sum(outs["outer"].value ** 2)

    grads = jax.grad(loss)(params)
    for k in ("w_x", "w_h"):
        g = np.asarray(grads[k])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0, k


def test_generation_empty_input_generates_nothing():
    """A sample with an empty in-link sequence generates length 0."""
    rng = np.random.RandomState(6)
    src = rng.randint(0, 11, (2, 4)).astype(np.int32)
    lens = np.array([4, 0], np.int32)
    tc = parse_str(GEN_INLINK)
    gm = GradientMachine(tc.model_config)
    params = gm.init_params(seed=8)
    batch = {"src": make_seq(None, jnp.asarray(lens), ids=jnp.asarray(src))}
    out, _ = gm.forward(params, batch, "gen")
    got_lens = np.asarray(out["gen"].seq_lengths)
    assert got_lens[1] == 0, got_lens
    assert got_lens[0] >= 1
