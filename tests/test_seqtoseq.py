"""End-to-end seqToseq NMT demo test: train the attention encoder-decoder
on the synthetic reverse-translation task, then beam-search generate and
check the model actually learned to translate.

Analog of the reference's trainer/tests/test_recurrent_machine_generation
(train a config, generate, compare output) — but checks task accuracy
instead of golden files so it is robust to implementation details.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "demo", "seqToseq")


@pytest.fixture(scope="module")
def workspace(tmp_path_factory):
    ws = tmp_path_factory.mktemp("seqtoseq")
    for f in os.listdir(DEMO):
        if f.endswith((".py", ".conf")):
            shutil.copy(os.path.join(DEMO, f), ws)
    (ws / "train.list").write_text("seed1\n")
    (ws / "test.list").write_text("seed2\n")
    return ws


def test_train_and_generate(workspace):
    from paddle_tpu.config import parse_config
    from paddle_tpu.trainer import Trainer
    from paddle_tpu.utils.flags import _Flags

    cwd = os.getcwd()
    os.chdir(workspace)
    try:
        cfg = parse_config(str(workspace / "train.conf"))
        flags = _Flags(config="train.conf", save_dir=str(workspace / "model"),
                       num_passes=25, log_period=100, use_tpu=False)
        trainer = Trainer(cfg, flags)
        trainer.train()
        final_cost = trainer.test()["cost"]
        assert final_cost < 2.5, f"NMT did not learn the reverse task (cost={final_cost})"

        gen_cfg = parse_config(str(workspace / "gen.conf"))
        gen_flags = _Flags(config="gen.conf",
                           init_model_path=str(workspace / "model" / "pass-00024"),
                           gen_result=str(workspace / "gen_result.txt"),
                           use_tpu=False)
        gen_trainer = Trainer(gen_cfg, gen_flags)
        results = gen_trainer.generate()
    finally:
        os.chdir(cwd)

    # reconstruct the expected translations from the provider
    sys.path.insert(0, str(workspace))
    try:
        import dataprovider as dp
        expected = [trg for _, trg in dp._pairs("seed2")]
    finally:
        sys.path.remove(str(workspace))

    got = []
    for ids, _, _, _ in results:
        for b in range(ids.shape[0]):
            row = ids[b].tolist()
            row = row[: row.index(1)] if 1 in row else row
            got.append(row)
    assert len(got) == len(expected)
    exact = sum(g == e for g, e in zip(got, expected))
    acc = exact / len(expected)
    assert acc > 0.5, f"beam search translations wrong: {acc:.0%} exact match " \
                      f"(e.g. got {got[:3]} want {expected[:3]})"

    # the result file has index lines + beam lines
    lines = (workspace / "gen_result.txt").read_text().splitlines()
    assert lines[0] == "0"
    assert "\t" in lines[1]
