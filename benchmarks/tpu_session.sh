#!/bin/bash
# Full TPU measurement session. Run automatically by tpu_watcher.sh the
# moment a chip claim succeeds, or by hand when the tunnel is known-up.
#
# Legs: bench all (bf16 production config, xplane trace of the headline
# window), f32 ResNet A/B, scan_unroll A/B on the recurrent legs, then a
# trace summary. Raw output lands in benchmarks/RESULTS_tpu_session_raw.txt
# inside the repo working tree so the driver's end-of-round auto-commit
# captures the numbers even if no agent is running when they arrive.
cd "$(dirname "$0")/.." || exit 1
# each session writes its own file, appended to the cumulative raw log at
# the end — the formatter sees exactly one session, so re-runs can never
# duplicate or misattribute earlier sessions' rows
CUM=benchmarks/RESULTS_tpu_session_raw.txt
OUT=$(mktemp /tmp/tpu_session_XXXX.txt)
ERR=/tmp/tpu_session_err.log
echo "=== TPU session $(date -u)" >> $OUT
mkdir -p benchmarks/traces
# headline: all three legs, bf16, trace captured
PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces PADDLE_TPU_BENCH_BUDGET=1400 \
  timeout 1500 python bench.py >> $OUT 2>$ERR
echo "--- f32 resnet A/B" >> $OUT
PADDLE_TPU_BENCH_DTYPE=float32 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py resnet >> $OUT 2>>$ERR
echo "--- resnet s2d stem A/B" >> $OUT
PADDLE_TPU_BENCH_S2D=1 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py resnet >> $OUT 2>>$ERR
for u in 4 8; do
  # SPL pinned to 1: the lstm leg's default is now k=8, and these rows
  # must stay comparable with earlier k=1 unroll measurements
  echo "--- unroll=$u lstm+nmt (k=1 control)" >> $OUT
  PADDLE_TPU_BENCH_UNROLL=$u PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
    PADDLE_TPU_BENCH_BUDGET=600 \
    timeout 700 python bench.py lstm >> $OUT 2>>$ERR
  PADDLE_TPU_BENCH_UNROLL=$u PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
    PADDLE_TPU_BENCH_BUDGET=600 \
    timeout 700 python bench.py nmt >> $OUT 2>>$ERR
done
# fused-launch A/B vs the k=1 control (the lstm leg DEFAULTS to k=8 on
# the accelerator now, so the control is the pinned run)
echo "--- steps_per_launch=1 lstm control" >> $OUT
PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 PADDLE_TPU_BENCH_BUDGET=600 \
  timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- steps_per_launch=8 nmt" >> $OUT
PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=8 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# fused Pallas recurrent kernel A/B (whole scan in one kernel launch;
# the nmt leg exercises the GRU kernel through the lowered encoder).
# lstm runs both at the k=8 default and a pinned k=1 control
echo "--- pallas_rnn lstm (k=8 default)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=600 \
  timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas_rnn lstm (k=1 control)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
  PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas_rnn nmt" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# per-leg traces for the recurrent flagships (the headline trace above
# covers resnet only)
for leg in lstm nmt; do
  echo "--- traced $leg" >> $OUT
  mkdir -p benchmarks/traces_$leg
  PADDLE_TPU_BENCH_TRACE_LEG=$leg PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces_$leg \
    PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py $leg >> $OUT 2>>$ERR
done
echo "--- trace summary (resnet)" >> $OUT
python benchmarks/trace_summary.py benchmarks/traces 15 >> $OUT 2>>$ERR
for leg in lstm nmt; do
  echo "--- trace summary ($leg)" >> $OUT
  python benchmarks/trace_summary.py benchmarks/traces_$leg 15 >> $OUT 2>>$ERR
done
echo "=== session done $(date -u)" >> $OUT
cat $OUT >> $CUM
# format measured rows into the append-only log so an unattended
# recovery still leaves RESULTS.md complete
python benchmarks/append_results.py $OUT >> $ERR 2>&1 || true
