#!/bin/bash
# Full TPU measurement session. Run automatically by tpu_watcher.sh the
# moment a chip claim succeeds, or by hand when the tunnel is known-up.
#
# LEG ORDER IS PRIORITY ORDER: the round-4 tunnel window lasted ~3h and
# this session is ~3.3h if everything runs — the unmeasured round-4 perf
# queue (pallas kernels, fused launches) must land BEFORE the A/B
# controls, so a window that dies mid-session still measured the things
# that decide defaults. Raw output lands in
# benchmarks/RESULTS_tpu_session_raw.txt inside the repo working tree so
# the driver's end-of-round auto-commit captures the numbers even if no
# agent is running when they arrive.
cd "$(dirname "$0")/.." || exit 1
# the in-flight session file lives IN THE REPO: if the tunnel wedges
# mid-session (the round-4 failure mode), the driver's end-of-round
# auto-commit still captures every completed leg. PID-unique name so a
# manual run and a watcher-fired run can overlap without interleaving.
# On clean completion it is appended to the cumulative raw log and
# removed — the formatter sees exactly one session per file, so re-runs
# can never duplicate earlier rows.
CUM=benchmarks/RESULTS_tpu_session_raw.txt
OUT=benchmarks/RESULTS_tpu_session_partial.$$.txt
ERR=/tmp/tpu_session_err.log
# salvage any leftover partial from a previously wedged session FIRST —
# its rows exist nowhere else (the formatter never ran for it)
for stale in benchmarks/RESULTS_tpu_session_partial.*.txt; do
  if [ -s "$stale" ] && [ "$stale" != "$OUT" ]; then
    echo "salvaging wedged-session partial $stale" >&2
    python benchmarks/append_results.py "$stale" >> $ERR 2>&1 || true
    cat "$stale" >> $CUM && rm -f "$stale"
  fi
done
: > $OUT
echo "=== TPU session $(date -u)" >> $OUT
mkdir -p benchmarks/traces
# LEG ORDER: the round's two OPEN A/Bs first (their controls are stable
# across windows: resnet B=256 measured 2182-2220 over five sessions,
# nmt defaults 599.3-600.4k), then the composed headline as the
# same-window control + driver artifact.
# 1a) gram conv-stats A/B (input-side BN statistics for 1x1 expand +
#     stride-2 projection convs, pure XLA —
#     layers/vision.py _publish_gram_stats): the round-5 rung at the
#     resnet reduce bottleneck. (The "pallas" mode of the same knob is
#     a measured end-to-end loser — layout copies — not re-run here.)
echo "--- resnet conv-stats A/B (gram input-side BN stats)" >> $OUT
mkdir -p benchmarks/traces_gram
PADDLE_TPU_BENCH_CONV_STATS=gram PADDLE_TPU_BENCH_RESNET_B=256 \
  PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces_gram \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py resnet >> $OUT 2>$ERR
# 1b) fused attention-GRU decoder A/B (ops/pallas_attention_gru): the
#     whole decoder time loop in one pallas launch — the round-5 NMT
#     rung (decoder scan/while is 56.6% of the traced step). First-ever
#     hardware compile; bench falls back to the scan on a Mosaic
#     rejection, so the leg budget is safe either way.
echo "--- nmt fused-decoder A/B (pallas attention-GRU)" >> $OUT
mkdir -p benchmarks/traces_decoder
PADDLE_TPU_BENCH_PALLAS_DECODER=1 PADDLE_TPU_BENCH_TRACE_LEG=nmt \
  PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces_decoder \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# 1b2) composed candidate: decoder kernel + flat interface together
#      (the default config if 1b and 1d individually win)
echo "--- nmt fused-decoder + flat (composed)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_DECODER=1 PADDLE_TPU_PALLAS_FLAT=1 \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# 1c) headline: all three legs, bf16, trace captured (same-window
#     control for the A/Bs above + the driver-facing composed numbers).
#     The literal "headline" marker matters: append_results.py treats
#     that context as the production configuration when refreshing
#     measured_tpu.json (a later A/B row must not overwrite it).
echo "--- headline" >> $OUT
PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces PADDLE_TPU_BENCH_BUDGET=1400 \
  timeout 1500 python bench.py >> $OUT 2>>$ERR
# 1d) transpose-free ("flat") recurrent-kernel interface A/B: the
#     kernels read the x-projection through a free [B, T*width] reshape
#     instead of the materialized time-major swap (the x-projection
#     backward transpose was 16.9% of the pallas-leg step). Both
#     recurrent legs; scan-fallback-safe like every pallas leg.
echo "--- pallas flat-interface lstm (k=8)" >> $OUT
PADDLE_TPU_PALLAS_FLAT=1 PADDLE_TPU_BENCH_PALLAS_RNN=1 \
  PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas flat-interface nmt (k=8)" >> $OUT
PADDLE_TPU_PALLAS_FLAT=1 PADDLE_TPU_BENCH_PALLAS_RNN=1 \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# 2) the round-4 unmeasured queue: fused Pallas recurrent kernels
#    (whole scan in one kernel launch; first-ever hardware compile —
#    bench falls back gracefully if Mosaic rejects them) and fused
#    launches on nmt. The nmt leg exercises the GRU kernel through the
#    lowered encoder.
echo "--- pallas_rnn lstm (k=8 default)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=600 \
  timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas_rnn lstm (k=1 control)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
  PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas_rnn nmt" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
echo "--- steps_per_launch=8 nmt" >> $OUT
PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=8 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
echo "--- pallas_rnn + steps_per_launch=8 nmt (combined)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=8 \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
# 3) stem space-to-depth A/B
echo "--- resnet s2d stem A/B" >> $OUT
PADDLE_TPU_BENCH_S2D=1 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py resnet >> $OUT 2>>$ERR
# 4) per-leg traces for the recurrent flagships on CURRENT HEAD (the
#    committed round-4 summaries predate the BN/CE rework)
for leg in lstm nmt; do
  echo "--- traced $leg" >> $OUT
  mkdir -p benchmarks/traces_$leg
  PADDLE_TPU_BENCH_TRACE_LEG=$leg PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces_$leg \
    PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py $leg >> $OUT 2>>$ERR
done
# 5) controls: f32 resnet, k=1 lstm, scan-unroll sweeps
echo "--- f32 resnet A/B" >> $OUT
PADDLE_TPU_BENCH_DTYPE=float32 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py resnet >> $OUT 2>>$ERR
echo "--- steps_per_launch=1 lstm control" >> $OUT
PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 PADDLE_TPU_BENCH_BUDGET=600 \
  timeout 700 python bench.py lstm >> $OUT 2>>$ERR
for u in 4 8; do
  # SPL pinned to 1: the lstm leg's default is now k=8, and these rows
  # must stay comparable with earlier k=1 unroll measurements
  echo "--- unroll=$u lstm+nmt (k=1 control)" >> $OUT
  PADDLE_TPU_BENCH_UNROLL=$u PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
    PADDLE_TPU_BENCH_BUDGET=600 \
    timeout 700 python bench.py lstm >> $OUT 2>>$ERR
  PADDLE_TPU_BENCH_UNROLL=$u PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
    PADDLE_TPU_BENCH_BUDGET=600 \
    timeout 700 python bench.py nmt >> $OUT 2>>$ERR
done
# 5b) generation throughput (beam search; lowest priority — quality
#     parity workload, not a BASELINE headline)
echo "--- nmt generation (beam search)" >> $OUT
PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py gen >> $OUT 2>>$ERR
# 6) trace summaries
echo "--- trace summary (resnet)" >> $OUT
python benchmarks/trace_summary.py benchmarks/traces 15 >> $OUT 2>>$ERR
echo "--- trace summary (gram resnet)" >> $OUT
python benchmarks/trace_summary.py benchmarks/traces_gram 15 >> $OUT 2>>$ERR
echo "--- trace summary (fused-decoder nmt)" >> $OUT
python benchmarks/trace_summary.py benchmarks/traces_decoder 15 >> $OUT 2>>$ERR
for leg in lstm nmt; do
  echo "--- trace summary ($leg)" >> $OUT
  python benchmarks/trace_summary.py benchmarks/traces_$leg 15 >> $OUT 2>>$ERR
done
echo "=== session done $(date -u)" >> $OUT
# format measured rows into the append-only log (also refreshes
# measured_tpu.json for bench.py's outage-time last_measured embedding),
# THEN fold the session file into the cumulative log and remove it
python benchmarks/append_results.py $OUT >> $ERR 2>&1 || true
# exit status tells the watcher whether THIS session produced any real
# TPU rows (the watcher must not trust a grep of the cumulative log —
# earlier sessions' rows would make it trivially true)
grep -q '"backend": "[^c]' $OUT
ok=$?
cat $OUT >> $CUM && rm -f $OUT
exit $ok
