"""One un-supervised TPU claim attempt: import jax, list devices, run a
small matmul. Exit 0 only if the accelerator actually executed work.

Run this ONLY from the recovery watcher (benchmarks/tpu_watcher.sh) or by
hand in a disposable shell — it claims the chip in-process, so a wedged
tunnel makes it hang ~25 min before failing UNAVAILABLE. Everything else
(bench.py, tests) must keep probing via
paddle_tpu.utils.backend_guard.probe_backend (subprocess + abandon-on-timeout
timeout).
"""
import time

t0 = time.time()
import jax
import jax.numpy as jnp

print("import", round(time.time() - t0, 1), flush=True)
t0 = time.time()
d = jax.devices()
print("devices", d, round(time.time() - t0, 1), flush=True)
assert any(dev.platform != "cpu" for dev in d), f"no accelerator in {d}"
t0 = time.time()
x = jnp.ones((1024, 1024), jnp.bfloat16)
y = (x @ x).block_until_ready()
print("matmul ok", round(time.time() - t0, 1), flush=True)
