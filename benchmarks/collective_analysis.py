"""Scaling-efficiency analysis: per-step collective volume from the
GSPMD-partitioned HLO.

BASELINE.md's north star includes "linear scaling 8 -> 64 chips". Real
multi-chip hardware is not reachable from this environment, but the
communication volume that DETERMINES scaling is: XLA inserts the
collectives during SPMD partitioning, and the partitioned HLO (compiled
against a virtual 8-device CPU mesh — same GSPMD pass as TPU) exposes
every all-reduce/all-gather/reduce-scatter/collective-permute with its
shape. This tool compiles the real sharded train step, sums collective
bytes per step, and compares the ICI time they imply against the
measured per-chip compute time — the scaling-book recipe for predicting
parallel efficiency.

Collective bytes are counted at the OUTPUT shape of each op (a ring
all-reduce moves ~2x that over the slowest link; the report applies the
ring factor). Async pairs (all-reduce-start/-done, TPU post-optimization
form) are counted at the -start op only. Collectives living inside a
while-loop BODY COMPUTATION (transitively, through fusions/calls)
execute once per scan step — reported separately with a pessimistic
T-fold bound, since XLA-TPU's while-loop all-reduce code motion is what
normally hoists them and this tool may be reading a CPU compile.

Gradient sizes are batch-independent, so small spatial configs give the
same collective volume as the bench shapes.

Usage: python benchmarks/collective_analysis.py  (CPU; forces the
virtual 8-device mesh itself)
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "s64": 8, "u64": 8, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
                "pred": 1}

_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                "collective-permute", "all-to-all")

# v5e ICI: 1600 Gbps per chip (Cloud TPU public spec)
_ICI_BYTES_PER_S = 200e9


def _shape_bytes(shape_text: str) -> int:
    """Bytes of an HLO shape string: 'f32[512,128]{1,0}' or a tuple
    '(f32[512,128]{1,0}, f32[512]{0}, ...)'."""
    total = 0
    for dtype, dims in re.findall(r"(\w+)\[([\d,]*)\]", shape_text):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        total += elems * _DTYPE_BYTES.get(dtype, 4)
    return total


# computation headers look like `%region_0.123 (arg: (s32[], ...)) -> ... {`
# — the parameter list may NEST parens (tuple params), so don't try to
# match it; the name + "(" + trailing "->"/"{" is discriminating enough
_COMP_HEADER = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(")


def _computations(hlo_text: str):
    """{computation name: block text} from HLO module text."""
    comps = {}
    name, buf = None, []
    for line in hlo_text.splitlines():
        m = _COMP_HEADER.match(line)
        if m and "->" in line and line.rstrip().endswith("{"):
            name, buf = m.group(1), []
            comps[name] = buf
            continue
        if name is not None:
            if line.startswith("}"):
                name = None
            else:
                buf.append(line)
    return {k: "\n".join(v) for k, v in comps.items()}


def _loop_computations(comps):
    """Names of computations reachable from any while-loop BODY (through
    calls/fusions/to_apply/conditionals) — their instructions execute
    once per loop iteration."""
    edges = {}
    roots = set()
    ref = re.compile(
        r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)"
        r"|branch_computations=\{([^}]*)\}")
    for cname, body in comps.items():
        outs = set()
        for m in ref.finditer(body):
            if m.group(1):
                outs.add(m.group(1))
            else:
                outs.update(x.strip().lstrip("%")
                            for x in m.group(2).split(",") if x.strip())
        edges[cname] = outs
        for m in re.finditer(r"body=%?([\w.\-]+)", body):
            roots.add(m.group(1))
    seen = set()
    stack = list(roots)
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        stack.extend(edges.get(c, ()))
    return seen


def collective_bytes(hlo_text: str):
    """{op kind: (count, total output bytes, in-loop bytes)} from the
    partitioned HLO text. Tuple-shaped collectives (XLA combines several
    gradient buffers into one all-reduce) are summed over their members;
    async -start/-done pairs count once at -start. in-loop = the op's
    instruction lives in a computation reachable from a while body, so
    it executes once PER iteration."""
    comps = _computations(hlo_text)
    if not comps:  # fragment without computation headers: one block
        comps = {"<fragment>": hlo_text}
    in_loop_comps = _loop_computations(comps)
    out = {}
    names = "|".join(_COLLECTIVES)
    pat = re.compile(
        rf"= (\([^)]*\)|\w+\[[\d,]*\]\S*) ({names})(-start)?\(")
    for cname, body in comps.items():
        looped = cname in in_loop_comps
        for m in pat.finditer(body):
            shape_text, op = m.group(1), m.group(2)
            nbytes = _shape_bytes(shape_text)
            c, b, lb = out.get(op, (0, 0, 0))
            out[op] = (c + 1, b + nbytes, lb + (nbytes if looped else 0))
    return out


def _sharded_step_hlo(tc, batch, mesh_shape):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from __graft_entry__ import _train_step
    from paddle_tpu.graph import GradientMachine
    from paddle_tpu.graph.machine import compute_dtype_of
    from paddle_tpu.optimizer import Updater
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.spmd import (
        _opt_state_sharding,
        _param_shardings,
        batch_sharding,
    )

    gm = GradientMachine(tc.model_config,
                         compute_dtype=compute_dtype_of(tc.opt_config))
    updater = Updater(tc.opt_config, tc.model_config)
    params = gm.init_params(seed=1)
    opt_state = updater.init_state(params)
    mesh = make_mesh(mesh_shape)
    grad_fn = gm.grad_fn(remat=tc.opt_config.remat)
    # the dryruns' shared one-train-step closure — the same step body the
    # driver gate compiles, not a local replica
    step = _train_step(grad_fn, updater)

    # the same jit shard_train_step builds lazily (spmd.py:281-297),
    # constructed eagerly so we can lower without executing
    param_shards = _param_shardings(mesh, gm)
    repl = NamedSharding(mesh, P())
    bsh = batch_sharding(mesh)
    p_spec = {k: param_shards.get(k, repl) for k in params}
    o_spec = _opt_state_sharding(mesh, param_shards, opt_state)
    b_spec = jax.tree_util.tree_map(lambda _: bsh, batch)
    fn = jax.jit(step, in_shardings=(p_spec, o_spec, b_spec, repl, repl),
                 out_shardings=(p_spec, o_spec, None, None))
    B = next(iter(batch.values())).batch_size
    lowered = fn.lower(params, opt_state, batch,
                       jax.random.PRNGKey(0), jnp.asarray(float(B)))
    return lowered.compile().as_text()


def analyze(name, tc, batch, mesh_shape, per_chip_step_s=None, scan_steps=1):
    hlo = _sharded_step_hlo(tc, batch, mesh_shape)
    cols = collective_bytes(hlo)
    total = sum(b for _, b, _lb in cols.values())
    in_loop = sum(lb for _, _b, lb in cols.values())
    n_params = sum(p.size for p in tc.model_config.parameters)
    print(f"== {name} (mesh {mesh_shape})")
    for op, (c, b, lb) in sorted(cols.items()):
        loop_note = f" (in-loop {lb / 1e6:.2f} MB per iteration)" if lb else ""
        print(f"  {op:20s} x{c:<3d} {b / 1e6:9.2f} MB{loop_note}")
    print(f"  params: {n_params / 1e6:.2f}M; collective total "
          f"{total / 1e6:.2f} MB/step (output-shape basis, in-loop "
          f"counted once)")
    if per_chip_step_s:
        # ring all-reduce moves ~2x the buffer across the slowest link
        def verdict(ratio):
            return ("overlappable" if ratio < 0.2 else
                    "partially hidden" if ratio < 1.0 else "comm-bound")

        ici_s = 2.0 * total / _ICI_BYTES_PER_S
        r = ici_s / per_chip_step_s
        print(f"  measured per-chip step {per_chip_step_s * 1e3:.1f} ms vs "
              f"ring-ICI {ici_s * 1e3:.2f} ms -> comm/compute = {r:.4f} "
              f"({verdict(r)})")
        if in_loop and scan_steps > 1:
            worst = 2.0 * (total + in_loop * (scan_steps - 1)) / _ICI_BYTES_PER_S
            rw = worst / per_chip_step_s
            print(f"  pessimistic bound if in-loop collectives are NOT "
                  f"hoisted (x{scan_steps} scan steps): ring-ICI "
                  f"{worst * 1e3:.2f} ms -> comm/compute = {rw:.4f} "
                  f"({verdict(rw)})")
    return cols, total


def main():
    from paddle_tpu.utils.backend_guard import ensure_cpu_mesh

    ensure_cpu_mesh(8)
    from paddle_tpu.flagship import (example_batch, flagship_config,
                                     make_image_batch, resnet_config)

    # LSTM classifier at bench hidden size (grads batch-independent);
    # scan_steps = the bench T so the unhoisted bound is honest
    tc = flagship_config(dict_dim=10000, emb_dim=256, hidden=512, classes=2,
                         mesh_shape="data=8")
    tc.opt_config.dtype = "bfloat16"
    analyze("lstm_classifier dp=8", tc, example_batch(dict_dim=10000, B=16, T=16),
            "data=8", per_chip_step_s=16384 / 5549079.8, scan_steps=64)

    # ResNet-50: small spatial config — identical parameter set (global
    # pool), so identical gradient collectives as the 224px bench
    tc = resnet_config(50, 64, 1000)
    tc.opt_config.dtype = "bfloat16"
    analyze("resnet50 dp=8", tc, make_image_batch(16, 64, 1000), "data=8",
            per_chip_step_s=256 / 2215.1)


if __name__ == "__main__":
    main()
