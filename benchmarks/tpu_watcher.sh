#!/bin/bash
# Patient TPU recovery watcher (committed per round-3 verdict: the session
# must not depend on tribal knowledge living in /tmp).
#
# One chip-claim attempt per cycle via benchmarks/tpu_probe.py — the probe
# is left UN-killed (a SIGKILLed TPU-client holder wedges the tunnel for
# every later claimant), so a wedged attempt simply occupies its cycle for
# the ~25 min the tunnel takes to reject it. On the first successful claim
# it runs the full measurement session once (benchmarks/tpu_session.sh)
# and exits. Log: /tmp/tpu_recovery_probe.log.
#
# Usage: nohup benchmarks/tpu_watcher.sh [max_attempts] & disown
cd "$(dirname "$0")/.." || exit 1
LOG=/tmp/tpu_recovery_probe.log
MAX=${1:-72}
for i in $(seq 1 "$MAX"); do
  echo "=== attempt $i $(date -u)" >> $LOG
  if python benchmarks/tpu_probe.py >> $LOG 2>&1; then
    echo "RECOVERED $(date -u)" >> $LOG
    # the session exits 0 only if ITS OWN legs produced a real TPU row
    # (grepping the cumulative log would be trivially true from earlier
    # sessions) — a tunnel that re-wedged right after the probe must not
    # burn the one-shot session
    if bash benchmarks/tpu_session.sh; then
      echo "SESSION COMPLETE $(date -u)" >> $LOG
      exit 0
    fi
    echo "SESSION PRODUCED NO TPU NUMBERS — continuing to watch $(date -u)" >> $LOG
  fi
  sleep 300
done
echo "GAVE UP after $MAX attempts $(date -u)" >> $LOG
exit 1
