"""Microbench: flash-attention pallas kernel vs XLA attention on TPU.

    python benchmarks/attn_bench.py [T ...]

Prints fwd+bwd step time and achieved context length for both paths.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from paddle_tpu.ops.pallas_attention import flash_attention
from paddle_tpu.parallel import sequence_parallel as sp


def bench(fn, q, k, v, steps=10):
    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    out = g(q, k, v)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q, k, v)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def main():
    on_tpu = jax.default_backend() == "tpu"
    cli_ts = [int(t) for t in sys.argv[1:]]
    if on_tpu:
        Ts = cli_ts or [1024, 4096, 8192]
        B, H, D = 4, 8, 64
    else:
        # any non-TPU backend: pallas only runs interpreted — tiny
        # shapes, smoke not perf
        print("no TPU backend: interpret-mode smoke at toy shapes "
              "(timings are NOT kernel performance)")
        Ts = cli_ts or [256]
        B, H, D = 1, 2, 64
    for T in Ts:
        rng = np.random.RandomState(0)
        mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        flash = bench(lambda q, k, v: flash_attention(
            q, k, v, causal=True, interpret=not on_tpu), q, k, v, steps=10 if on_tpu else 1)
        try:
            xla = bench(_xla_attn, q, k, v)
        except Exception:  # OOM at long T is the point
            xla = float("nan")
        print(f"T={T:6d}  flash={flash*1e3:8.2f} ms  xla={xla*1e3:8.2f} ms  "
              f"speedup={xla/flash:5.2f}x")


def _xla_attn(q, k, v):
    D = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(D, q.dtype))
    T = q.shape[1]
    mask = jnp.tril(jnp.ones((T, T), bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


if __name__ == "__main__":
    main()
