"""Summarize a jax.profiler xplane trace: top ops by device self-time.

    python benchmarks/trace_summary.py /path/to/trace_dir [N]

Walks the newest `*.xplane.pb` under the trace dir (written by
`jax.profiler.trace` / `--profile_dir`), accumulates event durations per
op on the device planes (TPU or CPU), and prints the top-N table plus
totals — the quick look that tells you whether the step is matmul-bound
(good: MXU busy) or drowning in transposes/copies, without opening
tensorboard. Pure protobuf walking via tensorboard_plugin_profile's
schema; no TF session anything.
"""

from __future__ import annotations

import collections
import glob
import os
import re
import sys

# HLO SSA suffixes on per-op event names ("dot.4", "tanh.5.clone",
# "fusion.26.remat") — newer profilers emit the bare HLO instruction
# name on the thread-pool lines, so summing requires folding the
# numbered instances back onto their opcode
_SSA_SUFFIX_RE = re.compile(r"(\.\d+)+(\.clone\d*|\.remat\d*)*$")


def _canonical_op(name: str) -> str:
    """Fold one HLO instruction name to its opcode ("dot.4" -> "dot")."""
    return _SSA_SUFFIX_RE.sub("", name.split(" = ", 1)[0])


def _find_xplanes(trace_dir: str):
    pats = [
        os.path.join(trace_dir, "**", "*.xplane.pb"),
    ]
    files: list = []
    for p in pats:
        files.extend(glob.glob(p, recursive=True))
    return sorted(files, key=os.path.getmtime)


def _xplane_pb2():
    candidates = (
        "tensorflow.tsl.profiler.protobuf.xplane_pb2",  # this image's TF
        "tsl.profiler.protobuf.xplane_pb2",             # standalone tsl
        "xprof.protobuf.xplane_pb2",                    # newer xprof wheels
    )
    import importlib

    errs = []
    for mod in candidates:
        try:
            return importlib.import_module(mod)
        except ImportError as e:
            errs.append(f"{mod}: {e}")
    raise ImportError("no xplane_pb2 found; tried:\n  " + "\n  ".join(errs))


def summarize(xplane_path: str):
    xplane_pb2 = _xplane_pb2()

    space = xplane_pb2.XSpace()
    with open(xplane_path, "rb") as f:
        space.ParseFromString(f.read())

    tables = {}
    for plane in space.planes:
        name = plane.name
        # device planes: "/device:TPU:0" (accelerators) or "/host:CPU"
        # (the XLA-CPU op line under a forced-CPU run); skip the python
        # host-thread and metadata planes
        if not (name.startswith("/device:") or "TPU" in name or name == "/host:CPU"):
            continue
        ev_names = {i: m.name for i, m in plane.event_metadata.items()}
        # accelerator planes carry whole-step span lines ("Steps",
        # "XLA Modules") next to the "XLA Ops" per-op line — summing those
        # double-counts and puts the module name on top. Prefer the "XLA
        # Ops" line when present (TPU/GPU). The /host:CPU plane (forced-CPU
        # runs) interleaves op events with python frames and PjRt wrapper
        # spans that ENCLOSE them on the same line, so there the filtering
        # must happen per EVENT: drop source refs ($file.py:..), C++
        # wrapper methods (Foo::Bar), python dispatch frames.
        op_lines = [l for l in plane.lines if l.name == "XLA Ops"]
        event_filter = None
        normalize = None
        if op_lines:
            lines = op_lines
        else:
            # host-CPU fallback. Two generations of layout: older jax put
            # op events on one anonymous line; current jax scatters them
            # over the runtime's thread-pool lines ("tf_XLAEigen/...",
            # "tf_XLATfrtCpuClient/...") interleaved with python frames
            # and C++ wrapper spans, and names events by HLO instruction
            # ("dot.4") instead of framework op — so filtering happens
            # per EVENT and instances fold onto their opcode.
            lines = [
                l
                for l in plane.lines
                if l.name not in ("Steps", "XLA Modules", "Framework Ops",
                                  "Source Code", "python")
            ]

            def event_filter(n):
                return not (
                    n.startswith("$")
                    or "::" in n
                    or n.startswith(("PjitFunction", "profiler", "Pjit", "jit("))
                )

            normalize = _canonical_op

        durs: collections.Counter = collections.Counter()
        count: collections.Counter = collections.Counter()
        for line in lines:
            for ev in line.events:
                n = ev_names.get(ev.metadata_id, "?")
                if event_filter is not None and not event_filter(n):
                    continue
                if normalize is not None:
                    n = normalize(n)
                durs[n] += ev.duration_ps
                count[n] += 1
        if durs:
            tables[name] = (durs, count)
    return tables


_CATEGORIES = (
    # (label, substrings matched against the lowered op name); first match
    # wins, so scan whiles (whole loop bodies, matmul + elementwise mixed)
    # are split out before the generic buckets
    ("scan/while bodies", ("%while",)),
    ("matmul/conv (MXU)", ("convolution", "dot")),
    ("dynamic-slice/update", ("dynamic-slice", "dynamic-update")),
    ("copy/transpose/reshape", ("copy", "transpose", "reshape", "bitcast")),
    ("reduce", ("reduce",)),
    ("fusion (elementwise etc.)", ("fusion",)),
)


def _category(name: str) -> str:
    # match the DEFINING name only ("%fusion.26" of
    # "%fusion.26 = bf16[...] fusion(f32[...] %reshape.4582, ...)") —
    # the operand list repeats other ops' names and would misclassify
    low = name.split(" = ", 1)[0].lower()
    for label, keys in _CATEGORIES:
        if any(k in low for k in keys):
            return label
    return "other"


def print_summary(trace_dir: str, top: int = 20) -> int:
    files = _find_xplanes(trace_dir)
    if not files:
        print(f"no *.xplane.pb under {trace_dir}", file=sys.stderr)
        return 1
    path = files[-1]
    print(f"# {path}")
    for plane, (durs, count) in summarize(path).items():
        total_ps = sum(durs.values())
        print(f"\n== {plane}  (total {total_ps / 1e9:.3f} ms summed-event time)")
        # category roll-up first: the one-glance MXU-vs-overhead split
        cats: collections.Counter = collections.Counter()
        for name, ps in durs.items():
            cats[_category(name)] += ps
        for label, ps in cats.most_common():
            print(f"  {label:<28} {ps / 1e9:9.3f} ms {100.0 * ps / max(total_ps, 1):6.1f}%")
        print(f"\n{'op':<58} {'ms':>9} {'%':>6} {'n':>7}")
        for name, ps in durs.most_common(top):
            pct = 100.0 * ps / max(total_ps, 1)
            print(f"{name[:58]:<58} {ps / 1e9:9.3f} {pct:6.1f} {count[name]:7d}")
    return 0


if __name__ == "__main__":
    d = sys.argv[1] if len(sys.argv) > 1 else "."
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    sys.exit(print_summary(d, n))
