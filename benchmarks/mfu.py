"""MFU (model FLOPs utilization) accounting for bench.py.

MFU = model FLOPs per second / peak bf16 FLOPs of the chip. Since round
5 the model-FLOP count comes from an exact jaxpr walk of the per-step
train function (`paddle_tpu.ops.kernel_flops.train_step_flops`): dot and
conv FLOPs, scan bodies multiplied by their static length, pallas kernel
bodies multiplied by their grid size. XLA's own cost analysis
(`flops_of_compiled` below) remains as the fallback basis, but it counts
a scan/while body ONCE regardless of trip count and cannot see inside
pallas_call custom calls — which understated the recurrent legs' MFU
several-fold through round 4 (restated in RESULTS.md). When the
fallback is used with pallas kernels in the step, their analytic counts
(recorded at trace time) are added to partially compensate. `bench.py` can additionally
capture an xplane trace of the timed window (PADDLE_TPU_BENCH_TRACE_DIR)
for profile-level verification of the step time; the trace is for
inspection, the MFU number printed in the bench JSON comes from the
formula above.

Peak numbers are per jax device (= one chip on v4+), bf16, from Google's
published TPU specs. Unknown device kinds yield None (MFU omitted, never
guessed).
"""

from __future__ import annotations

from typing import Optional

# the peak table lives with the FLOP accounting in the package (the
# trainer's MFU logging uses it too); re-exported here for callers
from paddle_tpu.ops.kernel_flops import peak_tflops  # noqa: F401


def flops_of_compiled(compiled) -> Optional[float]:
    """FLOPs of one execution of an AOT-compiled jit (XLA cost analysis).

    The caller compiles once (``jitted.lower(*args).compile()``) and uses
    the SAME executable for the timed loop, so the analysis describes
    exactly what ran."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        return float(flops) if flops else None
    except Exception:
        return None


def mfu(flops_per_step: Optional[float], step_time_s: float,
        device_kind: str) -> Optional[float]:
    """Fraction of peak bf16 FLOP/s sustained; None if either the FLOP
    count or the chip's peak is unknown."""
    peak = peak_tflops(device_kind)
    if flops_per_step is None or peak is None or step_time_s <= 0:
        return None
    return (flops_per_step / step_time_s) / (peak * 1e12)
