"""Format a TPU measurement session into RESULTS.md table rows.

Run by benchmarks/tpu_session.sh after the legs finish (or by hand):
parses the JSON lines in RESULTS_tpu_session_raw.txt, keeps the most
complete line per configuration, and appends measured rows to
benchmarks/RESULTS.md — so even an unattended recovery (watcher fires,
driver auto-commits) leaves the append-only log fully formatted.

Only lines with a non-CPU backend become rows; CPU smoke lines are
session plumbing, not measurements.
"""

from __future__ import annotations

import json
import os
import sys
from datetime import datetime, timezone

HERE = os.path.dirname(os.path.abspath(__file__))


def parse_session(raw_path: str):
    """Yield (context, record) for the last JSON line of each section."""
    context = "headline"
    last: dict = {}
    order: list = []
    with open(raw_path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("--- "):
                context = line[4:]
                continue
            if line.startswith("=== "):
                context = "headline"
                continue
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("metric") == "bench_failed":
                continue
            key = (context, rec.get("metric"))
            if key not in last:
                order.append(key)
            last[key] = rec  # cumulative re-emits: keep the final one
    return [(ctx, last[(ctx, m)]) for ctx, m in order]


def _cell(text) -> str:
    """Sanitize arbitrary text (XLA errors carry newlines, pipes, and —
    via the axon compile helper — raw ANSI escape sequences) for a
    markdown table cell."""
    import re

    s = re.sub(r"\x1b\[[0-9;]*m", "", str(text))
    return s.replace("\x1b", "").replace("\n", " ").replace("|", "\\|")


def fmt_row(when: str, context: str, rec: dict) -> list:
    rows = []
    backend = rec.get("backend", "?")
    if backend in ("", "cpu"):
        return rows

    def one(metric, value, unit, extras):
        cfg = ", ".join(
            f"{k}={extras[k]}"
            for k in ("dtype", "batch", "mfu", "hw_flops_util", "remat",
                      "steps_per_launch", "pallas_rnn",
                      "device_kind", "skipped_rungs")
            if extras.get(k) is not None
        )
        if context != "headline":
            cfg = f"{context}; {cfg}"
        rows.append(
            f"| {when} | {_cell(metric)} | **{value} {unit}** | {_cell(cfg)} | "
            f"{backend} | RESULTS_tpu_session_raw.txt |"
        )

    one(rec.get("metric"), rec.get("value"), rec.get("unit"), rec)
    for leg, sub in (rec.get("legs") or {}).items():
        if "error" in sub:
            rows.append(
                f"| {when} | {_cell(leg)} | leg failed | {_cell(sub['error'])[:120]} | "
                f"{backend} | RESULTS_tpu_session_raw.txt |"
            )
        else:
            one(leg, sub.get("value"), sub.get("unit", ""), sub)
    return rows


def refresh_measured_json(session, when: str) -> int:
    """Update measured_tpu.json with the NEWEST real-TPU row per metric
    from this session (bench.py embeds the file as "last_measured" in its
    CPU-fallback JSON, so the driver artifact survives tunnel outages).
    Newest — not best — because the file must describe the current code;
    the append-only RESULTS.md keeps the full history. Returns rows
    updated."""
    import subprocess

    path = os.path.join(HERE, "measured_tpu.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception:
        doc = {"rows": {}}
    rows = doc.setdefault("rows", {})
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=HERE,
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        commit = None

    session_rows = {}  # metric -> (from_headline, entry)

    def one(context, metric, rec):
        if not metric or rec.get("value") is None:
            return
        entry = {k: v for k, v in rec.items()
                 if k not in ("metric", "legs", "vs_baseline", "last_measured")
                 and v is not None}
        entry.update(when_utc=when.replace(" ", "T"), commit=commit)
        headline = context == "headline"
        if not headline:
            entry["session_leg"] = context
        # the production configuration ("headline" = plain `bench all`)
        # must win over later A/B contexts (f32 control, pallas legs, ...)
        # for the same metric; A/B rows only fill metrics the headline
        # didn't measure this session
        prev = session_rows.get(metric)
        if prev is None or headline or not prev[0]:
            session_rows[metric] = (headline, entry)

    for context, rec in session:
        backend = rec.get("backend", "")
        if backend in ("", "cpu"):
            continue
        one(context, rec.get("metric"), rec)
        for leg, sub in (rec.get("legs") or {}).items():
            if "error" not in sub:
                one(context, leg, {**sub, "backend": backend})
    for metric, (_, entry) in session_rows.items():
        rows[metric] = entry
    if session_rows:
        # keep the provenance note in sync with the rows it describes —
        # a hand-written session date here goes stale on the next refresh
        doc["_comment"] = (
            "Newest measured real-TPU rows, one per metric (per-row "
            "when_utc/commit give each row's provenance; full raw log: "
            "RESULTS_tpu_session_raw.txt, formatted: RESULTS.md). "
            "bench.py embeds this under 'last_measured' whenever it "
            "falls back to CPU smoke, so the driver's BENCH artifact "
            "always carries the best available hardware evidence even "
            "during a tunnel outage. Refreshed automatically by "
            "append_results.py after each measurement session."
        )
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
    return len(session_rows)


def main(argv=None) -> int:
    raw = os.path.join(HERE, "RESULTS_tpu_session_raw.txt")
    results = os.path.join(HERE, "RESULTS.md")
    if argv and len(argv) > 0:
        raw = argv[0]
    if not os.path.exists(raw):
        print(f"no session file at {raw}", file=sys.stderr)
        return 1
    when = datetime.now(timezone.utc).strftime("%Y-%m-%d %H:%MZ")
    session = parse_session(raw)
    try:
        n = refresh_measured_json(session, when)
        if n:
            print(f"refreshed measured_tpu.json ({n} metrics)")
    except Exception as e:
        # a malformed measured_tpu.json must never cost an unattended
        # session its RESULTS.md rows — the append below always runs
        print(f"measured_tpu.json refresh failed: {e}", file=sys.stderr)
    rows: list = []
    for context, rec in session:
        rows.extend(fmt_row(when, context, rec))
    if not rows:
        print("session produced no TPU measurements; nothing appended")
        return 0
    # rows live in their own headed table section at EOF — the file ends
    # with prose between rounds, so bare pipe rows would not render
    section = "## Measured session rows (auto-appended by append_results.py)"
    existing = ""
    if os.path.exists(results):
        with open(results) as f:
            existing = f.read()
    with open(results, "a") as f:
        if section not in existing:
            f.write(f"\n{section}\n\n")
            f.write("| when | metric | value | config | backend | source |\n")
            f.write("|---|---|---|---|---|---|\n")
        f.write("\n".join(rows) + "\n")
    print(f"appended {len(rows)} measured rows to {results}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
