#!/bin/bash
# Follow-up measurement session: the fused Pallas recurrent kernels ONLY.
#
# The 2026-08-01 03:10Z session was the kernels' first-ever hardware
# compile and Mosaic rejected the mask block spec ((B, 1) over a [B, T]
# array — lane dim neither 128-divisible nor the full array); every
# pallas leg fell back to the scan path. This session re-runs exactly
# those legs after the [T, B, 1] mask re-layout, plus a trace capture if
# the kernel path wins. Run it only when the chip is known-free (the
# main session exited).
cd "$(dirname "$0")/.." || exit 1
CUM=benchmarks/RESULTS_tpu_session_raw.txt
OUT=benchmarks/RESULTS_tpu_session_partial.$$.txt
ERR=/tmp/tpu_session_pallas_err.log
: > $OUT
echo "=== TPU pallas follow-up session $(date -u)" >> $OUT
echo "--- pallas_rnn lstm (k=8 default)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=600 \
  timeout 700 python bench.py lstm >> $OUT 2>$ERR
echo "--- pallas_rnn lstm (k=1 control)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=1 \
  PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- pallas_rnn nmt" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_BUDGET=900 \
  timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
echo "--- pallas_rnn + steps_per_launch=8 nmt (combined)" >> $OUT
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_STEPS_PER_LAUNCH=8 \
  PADDLE_TPU_BENCH_BUDGET=900 timeout 1000 python bench.py nmt >> $OUT 2>>$ERR
echo "--- traced pallas lstm" >> $OUT
mkdir -p benchmarks/traces_pallas_lstm
PADDLE_TPU_BENCH_PALLAS_RNN=1 PADDLE_TPU_BENCH_TRACE_LEG=lstm \
  PADDLE_TPU_BENCH_TRACE_DIR=$PWD/benchmarks/traces_pallas_lstm \
  PADDLE_TPU_BENCH_BUDGET=600 timeout 700 python bench.py lstm >> $OUT 2>>$ERR
echo "--- trace summary (pallas lstm)" >> $OUT
python benchmarks/trace_summary.py benchmarks/traces_pallas_lstm 15 >> $OUT 2>>$ERR
echo "=== session done $(date -u)" >> $OUT
python benchmarks/append_results.py $OUT >> $ERR 2>&1 || true
grep -q '"backend": "[^c]' $OUT
ok=$?
cat $OUT >> $CUM && rm -f $OUT
exit $ok
