#!/usr/bin/env bash
# The pre-review analysis gate: `paddle lint` (static, PTL001-PTL008)
# then `paddle race` (dynamic: schedule explorer + lock-order /
# torn-read / lost-wakeup detectors), each against its checked-in
# baseline (lint: .paddle_lint_baseline.json, race:
# .paddle_race_baseline.json — BOTH empty; keep them that way).
#
# Wired into the test suite as tests/test_race.py's gate tests; run it
# directly before sending a PR that touches threads, locks, queues, or
# telemetry:
#
#   bin/check_analysis.sh [--schedules K]
#
# jax-free end to end, finishes in seconds. Exit: 0 clean, nonzero on
# any new finding (the offending findings are printed with replay
# seeds/traces).
set -euo pipefail
cd "$(dirname "$0")/.."

SCHEDULES=24
if [[ "${1:-}" == "--schedules" && -n "${2:-}" ]]; then
  SCHEDULES="$2"
fi

PY="${PYTHON:-python3}"

echo "== paddle lint =="
"$PY" -m paddle_tpu.cli lint paddle_tpu

echo "== paddle race (schedules=$SCHEDULES) =="
"$PY" -m paddle_tpu.cli race --schedules "$SCHEDULES"

echo "== paddle trace --selftest =="
# golden two-stream fixture through the full reconstruct/align/attribute
# path — jax-free, <5 s (doc/observability.md "Distributed tracing")
"$PY" -m paddle_tpu.cli trace --selftest

echo "== analysis gate clean =="
